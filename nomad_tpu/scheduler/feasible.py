"""Feasibility checking (reference scheduler/feasible.go, 1,587 LoC).

Host-side implementation of the 15 constraint operators with exact
reference semantics (feasible.go:833 checkConstraint, :793 resolveTarget,
:880 checkOrder int->float->lexical fallback, :1050 set-contains comma
split + trim). Everything is exposed both per-node (oracle / host path)
and as vectorized masks over node lists (the shape the tensorizer ships
to the TPU kernels).
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from ..structs import Constraint, Job, Node, TaskGroup, enums

# ---------------------------------------------------------------------------
# target resolution (reference feasible.go:793 resolveTarget)
# ---------------------------------------------------------------------------


def resolve_target(target: str, node: Node) -> Tuple[str, bool]:
    """Resolve an interpolation target like "${attr.kernel.name}" against a
    node. Returns (value, found). Non-${...} strings are literals."""
    if not target.startswith("${"):
        return target, True
    if target == "${node.unique.id}":
        return node.id, True
    if target == "${node.datacenter}":
        return node.datacenter, True
    if target == "${node.unique.name}":
        return node.name, True
    if target == "${node.class}":
        return node.node_class, True
    if target == "${node.pool}":
        return node.node_pool, True
    if target.startswith("${attr."):
        key = target[len("${attr."):-1]
        val = node.attributes.get(key)
        return ("" if val is None else str(val)), val is not None
    if target.startswith("${meta."):
        key = target[len("${meta."):-1]
        val = node.meta.get(key)
        return ("" if val is None else str(val)), val is not None
    if target.startswith("${device."):
        # device attribute targets are handled by the device allocator
        return "", False
    return "", False


def is_class_escaped(target: str) -> bool:
    """Whether a constraint target defeats computed-class memoization
    (reference scheduler/context.go:292-305 EvalEligibility escape set:
    anything node-unique)."""
    return (
        "${node.unique." in target
        or "${attr.unique." in target
        or "${meta.unique." in target
    )


# ---------------------------------------------------------------------------
# operator checks (reference feasible.go:833-1110)
# ---------------------------------------------------------------------------

_num_int = re.compile(r"^[+-]?\d+$")


def _check_order(operand: str, l: str, r: str) -> bool:
    """Integer comparison if both parse, else float, else lexical
    (reference feasible.go:880-940)."""
    if _num_int.match(l) and _num_int.match(r):
        li, ri = int(l), int(r)
    else:
        try:
            li, ri = float(l), float(r)
        except ValueError:
            li, ri = l, r
    if operand == "<":
        return li < ri
    if operand == "<=":
        return li <= ri
    if operand == ">":
        return li > ri
    if operand == ">=":
        return li >= ri
    return False


class _Version:
    """Minimal go-version-style version: dotted numeric segments with an
    optional -prerelease suffix (prerelease sorts before release)."""

    __slots__ = ("segments", "prerelease", "written")

    def __init__(self, s: str):
        s = s.strip().lstrip("v")
        if "+" in s:  # build metadata ignored
            s = s.split("+", 1)[0]
        if "-" in s:
            base, self.prerelease = s.split("-", 1)
        else:
            base, self.prerelease = s, ""
        segs = []
        for part in base.split("."):
            if not _num_int.match(part):
                raise ValueError(f"bad version segment {part!r} in {s!r}")
            segs.append(int(part))
        if not segs:
            raise ValueError(f"empty version {s!r}")
        self.written = len(segs)  # segments the user actually wrote ("~>" cares)
        while len(segs) < 3:
            segs.append(0)
        self.segments = tuple(segs)

    def _key(self):
        # a prerelease sorts before the release it prefixes
        return (self.segments, 0 if self.prerelease == "" else -1, self.prerelease)

    def __lt__(self, o):  # pragma: no cover - trivially exercised via cmp
        return (self.segments, self.prerelease == "", self.prerelease) < (
            o.segments, o.prerelease == "", o.prerelease)

    def cmp(self, o: "_Version") -> int:
        if self.segments != o.segments:
            return -1 if self.segments < o.segments else 1
        # equal segments: release > prerelease; prereleases compare lexically
        if self.prerelease == o.prerelease:
            return 0
        if self.prerelease == "":
            return 1
        if o.prerelease == "":
            return -1
        return -1 if self.prerelease < o.prerelease else 1


_ver_con = re.compile(r"^\s*(~>|>=|<=|!=|=|>|<)?\s*(.+?)\s*$")


def check_version_constraint(version_str: str, constraint_str: str,
                             cache: Optional[dict] = None) -> bool:
    """go-version style constraint check: comma-separated AND of
    "<op> <version>" clauses incl. pessimistic "~>"
    (reference feasible.go:948 checkVersionMatch)."""
    try:
        ver = _Version(version_str)
    except ValueError:
        return False
    key = constraint_str
    clauses = cache.get(key) if cache is not None else None
    if clauses is None:
        clauses = []
        try:
            for raw in constraint_str.split(","):
                m = _ver_con.match(raw)
                if not m or not m.group(2):
                    return False
                clauses.append((m.group(1) or "=", _Version(m.group(2))))
        except ValueError:
            clauses = False  # cache the parse failure
        if cache is not None:
            cache[key] = clauses
    if clauses is False:
        return False
    for op, target in clauses:
        c = ver.cmp(target)
        if op == "=" and c != 0:
            return False
        if op == "!=" and c == 0:
            return False
        if op == ">" and c != 1:
            return False
        if op == ">=" and c == -1:
            return False
        if op == "<" and c != -1:
            return False
        if op == "<=" and c == 1:
            return False
        if op == "~>":
            # pessimistic: >= target, < target with the second-to-last
            # *written* segment bumped ("~> 1.2" -> < 2.0.0, "~> 1.2.3"
            # -> < 1.3.0, "~> 1" -> < 2.0.0) — go-version semantics
            if c == -1:
                return False
            upper = list(target.segments)
            bump = max(0, target.written - 2)
            upper[bump] += 1
            for i in range(bump + 1, len(upper)):
                upper[i] = 0
            if ver.cmp(_Version(".".join(map(str, upper)))) != -1:
                return False
    return True


def _split_set(s: str) -> set:
    return {part.strip() for part in s.split(",")}


def check_constraint(operand: str, lval: str, rval: str, lfound: bool, rfound: bool,
                     regex_cache: Optional[dict] = None,
                     version_cache: Optional[dict] = None) -> bool:
    """Exact reference semantics (feasible.go:833 checkConstraint)."""
    if operand in (enums.CONSTRAINT_DISTINCT_HOSTS, enums.CONSTRAINT_DISTINCT_PROPERTY):
        return True  # handled by dedicated iterators
    if operand in ("=", "==", "is"):
        return lfound and rfound and lval == rval
    if operand in ("!=", "not"):
        # reference uses reflect.DeepEqual on possibly-missing values:
        # missing != present is true; missing != missing compares "" == ""
        if not lfound and not rfound:
            return False
        if lfound != rfound:
            return True
        return lval != rval
    if operand in ("<", "<=", ">", ">="):
        return lfound and rfound and _check_order(operand, lval, rval)
    if operand == enums.CONSTRAINT_IS_SET:
        return lfound
    if operand == enums.CONSTRAINT_IS_NOT_SET:
        return not lfound
    if operand in (enums.CONSTRAINT_VERSION, enums.CONSTRAINT_SEMVER):
        return lfound and rfound and check_version_constraint(lval, rval, version_cache)
    if operand == enums.CONSTRAINT_REGEX:
        if not (lfound and rfound):
            return False
        rx = regex_cache.get(rval) if regex_cache is not None else None
        if rx is None:
            try:
                rx = re.compile(rval)
            except re.error:
                if regex_cache is not None:
                    regex_cache[rval] = False
                return False
            if regex_cache is not None:
                regex_cache[rval] = rx
        if rx is False:
            return False
        return rx.search(lval) is not None
    if operand in (enums.CONSTRAINT_SET_CONTAINS, enums.CONSTRAINT_SET_CONTAINS_ALL):
        if not (lfound and rfound):
            return False
        have = _split_set(lval)
        return all(want in have for want in _split_set(rval))
    if operand == enums.CONSTRAINT_SET_CONTAINS_ANY:
        if not (lfound and rfound):
            return False
        have = _split_set(lval)
        return any(want in have for want in _split_set(rval))
    return False


def node_meets_constraint(c: Constraint, node: Node,
                          regex_cache: Optional[dict] = None,
                          version_cache: Optional[dict] = None) -> bool:
    lval, lfound = resolve_target(c.ltarget, node)
    rval, rfound = resolve_target(c.rtarget, node)
    return check_constraint(c.operand, lval, rval, lfound, rfound,
                            regex_cache, version_cache)


# ---------------------------------------------------------------------------
# vectorized masks — the bridge to the tensor layer
# ---------------------------------------------------------------------------


def constraint_mask(c: Constraint, nodes: Sequence[Node],
                    regex_cache: Optional[dict] = None,
                    version_cache: Optional[dict] = None) -> np.ndarray:
    """Boolean feasibility of one constraint over a node list. This is the
    host-side "precompile" step: regex/version/semver get parsed once and
    evaluated per *unique attribute value*, not per node."""
    out = np.empty(len(nodes), dtype=bool)
    memo: Dict[Tuple[str, bool, str, bool], bool] = {}
    for i, node in enumerate(nodes):
        lval, lfound = resolve_target(c.ltarget, node)
        rval, rfound = resolve_target(c.rtarget, node)
        key = (lval, lfound, rval, rfound)
        hit = memo.get(key)
        if hit is None:
            hit = check_constraint(c.operand, lval, rval, lfound, rfound,
                                   regex_cache, version_cache)
            memo[key] = hit
        out[i] = hit
    return out


def driver_mask(tg: TaskGroup, nodes: Sequence[Node]) -> np.ndarray:
    """DriverChecker (reference feasible.go:470): every task's driver must
    be present and healthy on the node."""
    drivers = {t.driver for t in tg.tasks}
    out = np.empty(len(nodes), dtype=bool)
    for i, node in enumerate(nodes):
        ok = True
        for d in drivers:
            if node.drivers.get(d):
                continue
            # fall back to fingerprinted attribute (reference checks
            # driver.<name> node attribute for compatibility)
            v = node.attributes.get(f"driver.{d}", "")
            if str(v).lower() in ("1", "true"):
                continue
            ok = False
            break
        out[i] = ok
    return out


def device_mask(tg: TaskGroup, nodes: Sequence[Node]) -> np.ndarray:
    """DeviceChecker (reference feasible.go:1259): node must have enough
    instances of each requested device type (ignoring current usage —
    usage is checked during ranking/fit)."""
    asks = []
    for t in tg.tasks:
        for d in t.resources.devices:
            asks.append(d)
    if not asks:
        return np.ones(len(nodes), dtype=bool)
    out = np.empty(len(nodes), dtype=bool)
    for i, node in enumerate(nodes):
        ok = True
        for ask in asks:
            have = 0
            for group in node.resources.devices:
                if group.matches(ask.name):
                    have += len(group.instance_ids)
            if have < ask.count:
                ok = False
                break
        out[i] = ok
    return out


def network_mask(tg: TaskGroup, nodes: Sequence[Node]) -> np.ndarray:
    """NetworkChecker (reference feasible.go:373): the requested network
    mode must be available on the node. "host" mode (and "" = default) is
    always available; "bridge" requires the bridge fingerprint; "cni/*"
    modes must be fingerprinted by name."""
    modes = set()
    for net in tg.networks:
        modes.add(net.mode or "host")
    for t in tg.tasks:
        for net in t.resources.networks:
            modes.add(net.mode or "host")
    modes.discard("host")
    if not modes:
        return np.ones(len(nodes), dtype=bool)
    out = np.empty(len(nodes), dtype=bool)
    for i, node in enumerate(nodes):
        have = {n.mode for n in node.resources.networks}
        ok = True
        for m in modes:
            if m in have:
                continue
            if m == "bridge" and str(node.attributes.get(
                    "network.bridge", "")).lower() in ("1", "true"):
                continue
            if m.startswith("cni/") and str(node.attributes.get(
                    f"plugins.cni.version.{m[4:]}", "")):
                continue
            ok = False
            break
        out[i] = ok
    return out


def host_volume_mask(tg: TaskGroup, nodes: Sequence[Node]) -> np.ndarray:
    """HostVolumeChecker (reference feasible.go:139): every host-type
    volume request must name a volume the node exposes; a read-write
    request needs a non-read-only host volume. Class-memoizable: host
    volumes ride the computed-class hash."""
    asks = [v for v in tg.volumes.values() if v.type == "host"]
    if not asks:
        return np.ones(len(nodes), dtype=bool)
    out = np.empty(len(nodes), dtype=bool)
    for i, node in enumerate(nodes):
        ok = True
        for req in asks:
            hv = node.host_volumes.get(req.source)
            if hv is None or (getattr(hv, "read_only", False)
                              and not req.read_only):
                ok = False
                break
        out[i] = ok
    return out


def csi_volume_mask(tg: TaskGroup, nodes: Sequence[Node],
                    snapshot, namespace: str = "default",
                    plan=None) -> np.ndarray:
    """CSIVolumeChecker (reference feasible.go:223): every csi-type
    request must name a registered volume whose topology admits the node
    and whose access mode has room for our claim. Writer exclusivity only
    counts LIVE claims not being stopped by the in-progress plan
    (volumes.live_blocking_writers) so updates/reschedules of the
    claiming job don't deadlock on their own claim while a scale-up's
    live sibling still blocks. NOT class-memoized — claims change
    independently of node classes."""
    from ..structs.volumes import MULTI_WRITER_MODES, live_blocking_writers

    asks = [v for v in tg.volumes.values() if v.type == "csi"]
    if not asks:
        return np.ones(len(nodes), dtype=bool)
    if snapshot is None:
        return np.zeros(len(nodes), dtype=bool)
    vols = []
    for req in asks:
        vol = snapshot.volume_by_id(req.source, namespace)
        if vol is None:
            return np.zeros(len(nodes), dtype=bool)
        if (not req.read_only and vol.access_mode not in MULTI_WRITER_MODES
                and live_blocking_writers(vol, snapshot, plan)):
            return np.zeros(len(nodes), dtype=bool)
        vols.append(vol)
    out = np.empty(len(nodes), dtype=bool)
    for i, node in enumerate(nodes):
        out[i] = all(v.schedulable_on(node.id) for v in vols)
    return out


def reserved_ports_mask(tg: TaskGroup, nodes: Sequence[Node],
                        proposed_allocs_fn) -> np.ndarray:
    """Static-port feasibility: every reserved port the group asks for
    must be free on the node given its proposed allocs (reference does
    this inside BinPackIterator via NetworkIndex; host-side here so the
    tensor path can fold it into the feasibility mask)."""
    asks = tg.combined_resources().reserved_port_asks()
    if not asks:
        return np.ones(len(nodes), dtype=bool)
    from ..structs.network import NetworkIndex

    want = [p for _, p in asks]
    out = np.empty(len(nodes), dtype=bool)
    for i, node in enumerate(nodes):
        idx = NetworkIndex(node)
        idx.add_allocs(proposed_allocs_fn(node.id))
        out[i] = not any(p in idx.used for p in want)
    return out


def job_constraints(job: Job, tg: TaskGroup) -> List[Constraint]:
    """Merged constraint set: job-level + group-level + every task's
    (reference stack pushes job then tg constraints through the chain)."""
    out = list(job.constraints) + list(tg.constraints)
    for t in tg.tasks:
        out.extend(t.constraints)
    return out


def feasible_mask_static(job: Job, tg: TaskGroup, nodes: Sequence[Node],
                         regex_cache: Optional[dict] = None,
                         version_cache: Optional[dict] = None) -> np.ndarray:
    """The node-attribute-only part of the feasibility mask: constraints
    + drivers + devices + network modes + host volumes. Depends only on
    node identity/attributes — cacheable per (task-group signature,
    node-set version) by the tensor layer (tg_mask_signature)."""
    mask = driver_mask(tg, nodes)
    if not mask.any():
        return mask
    mask &= device_mask(tg, nodes)
    mask &= network_mask(tg, nodes)
    mask &= host_volume_mask(tg, nodes)
    for c in job_constraints(job, tg):
        if not mask.any():
            break
        mask &= constraint_mask(c, nodes, regex_cache, version_cache)
    return mask


def tg_mask_signature(job: Job, tg: TaskGroup) -> tuple:
    """Cache key capturing every input of feasible_mask_static other than
    the node set itself."""
    drivers = tuple(sorted({t.driver for t in tg.tasks}))
    devs = tuple(sorted((d.name, d.count)
                        for t in tg.tasks for d in t.resources.devices))
    modes = set()
    for net in tg.networks:
        modes.add(net.mode or "host")
    for t in tg.tasks:
        for net in t.resources.networks:
            modes.add(net.mode or "host")
    hvols = tuple(sorted((v.source, v.read_only)
                         for v in tg.volumes.values() if v.type == "host"))
    cons = tuple((c.ltarget, c.operand, c.rtarget)
                 for c in job_constraints(job, tg))
    return (drivers, devs, tuple(sorted(modes)), hvols, cons)


def feasible_mask(job: Job, tg: TaskGroup, nodes: Sequence[Node],
                  regex_cache: Optional[dict] = None,
                  version_cache: Optional[dict] = None,
                  snapshot=None, plan=None) -> np.ndarray:
    """Full boolean feasibility mask for one task group over a node list:
    constraints + drivers + devices + volumes. Datacenter/pool/readiness
    filtering is assumed done upstream (reference readyNodesInDCsAndPool).
    `snapshot` powers the csi-volume claim check; without it csi-volume
    groups mask everything out."""
    mask = feasible_mask_static(job, tg, nodes, regex_cache, version_cache)
    if any(v.type == "csi" for v in tg.volumes.values()):
        mask = mask & csi_volume_mask(tg, nodes, snapshot, job.namespace, plan)
    return mask


# ---------------------------------------------------------------------------
# distinct_hosts / distinct_property (reference feasible.go:542,649)
# ---------------------------------------------------------------------------


def has_distinct_hosts(job: Job, tg: TaskGroup) -> bool:
    return any(
        c.operand == enums.CONSTRAINT_DISTINCT_HOSTS and _truthy(c.rtarget)
        for c in list(job.constraints) + list(tg.constraints)
    )


def _truthy(rtarget: str) -> bool:
    return rtarget in ("", "true", "True", "1")


def distinct_property_constraints(job: Job, tg: TaskGroup) -> List[Constraint]:
    return [
        c for c in list(job.constraints) + list(tg.constraints)
        if c.operand == enums.CONSTRAINT_DISTINCT_PROPERTY
    ]


def distinct_hosts_flags(job: Job, tg: TaskGroup) -> Tuple[bool, bool]:
    """(job_level, tg_level) distinct_hosts enablement — the single source
    of truth shared by the host iterator and the tensor lowering."""
    job_level = any(
        c.operand == enums.CONSTRAINT_DISTINCT_HOSTS and _truthy(c.rtarget)
        for c in job.constraints)
    tg_level = any(
        c.operand == enums.CONSTRAINT_DISTINCT_HOSTS and _truthy(c.rtarget)
        for c in tg.constraints)
    return job_level, tg_level


def distinct_hosts_mask(job: Job, tg: TaskGroup, nodes: Sequence[Node],
                        proposed_by_node) -> np.ndarray:
    """Mask out nodes already carrying an alloc of this job (job-level) or
    this task group (group-level) (reference feasible.go:542
    DistinctHostsIterator)."""
    job_level, tg_level = distinct_hosts_flags(job, tg)
    if not job_level and not tg_level:
        return np.ones(len(nodes), dtype=bool)
    out = np.ones(len(nodes), dtype=bool)
    for i, node in enumerate(nodes):
        for alloc in proposed_by_node(node.id):
            if alloc.job_id != job.id or alloc.namespace != job.namespace:
                continue
            if job_level or (tg_level and alloc.task_group == tg.name):
                out[i] = False
                break
    return out


def distinct_property_mask(job: Job, tg: TaskGroup, nodes: Sequence[Node],
                           all_job_allocs, node_by_id) -> np.ndarray:
    """Limit allocs per distinct value of a node property
    (reference scheduler/propertyset.go). rtarget is the max count per
    value (default 1)."""
    constraints = distinct_property_constraints(job, tg)
    if not constraints:
        return np.ones(len(nodes), dtype=bool)
    out = np.ones(len(nodes), dtype=bool)
    live_allocs = [a for a in all_job_allocs
                   if not a.terminal_status()]
    for c in constraints:
        try:
            limit = int(c.rtarget) if c.rtarget else 1
        except ValueError:
            limit = 1
        # count existing allocs per property value
        counts: Dict[str, int] = {}
        for alloc in live_allocs:
            anode = node_by_id(alloc.node_id)
            if anode is None:
                continue
            val, found = resolve_target(c.ltarget, anode)
            if found:
                counts[val] = counts.get(val, 0) + 1
        for i, node in enumerate(nodes):
            val, found = resolve_target(c.ltarget, node)
            if not found or counts.get(val, 0) >= limit:
                out[i] = False
    return out
