"""Preemption victim selection (reference scheduler/preemption.go, 779 LoC).

Implements the reference's heuristics:

- only allocations at least PRIORITY_DELTA (10) below the asking job's
  priority are evictable (preemption.go filterAndGroupPreemptibleAllocs);
- candidates are considered in ascending priority groups and chosen by
  resource distance — how closely the victim's resources match the
  remaining need (preemption.go basicResourceDistance) — stopping as
  soon as the ask fits, then redundant victims are dropped
  (filterSuperset);
- a victim whose task group is already at its migrate max_parallel in
  this selection takes a score penalty of MAX_PARALLEL_PENALTY (50) per
  excess eviction (preemption.go:16 maxParallelPenalty,
  scoreForTaskGroup);
- network preemption frees conflicting reserved ports / mbits by
  network resource distance (preemption.go PreemptForNetwork,
  networkResourceDistance);
- device preemption frees device-group instances, preferring the victim
  set with minimal net priority, largest holders first
  (preemption.go PreemptForDevice, selectBestAllocs).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..structs import allocs_fit
from ..structs.alloc import Allocation
from ..structs.resources import RESOURCE_DIMS

# reference preemption.go:26 — "skip allocs whose priority is within a
# delta of 10"
PRIORITY_DELTA = 10
# reference preemption.go:16 maxParallelPenalty
MAX_PARALLEL_PENALTY = 50.0


def is_preemptible(alloc: Allocation, current_priority: int) -> bool:
    return (alloc.job is not None
            and current_priority - alloc.job.priority >= PRIORITY_DELTA
            and alloc.should_count_for_usage())


def victim_candidates(proposed: Sequence[Allocation],
                      current_priority: int) -> List[Allocation]:
    """Eligible victims in the CANONICAL COLUMN ORDER the in-kernel
    prefix rule consumes: priority ascending (the reference's
    filterAndGroupPreemptibleAllocs group order), alloc id ascending
    within a priority tie so the order is deterministic across
    processes (leader failover replaying an eval must select the same
    victims). This is the single eligibility definition shared by the
    exact scanner below, the tensor victim-column builder
    (tensor/cluster.build_victim_tensors), and the preempt_solve kernel
    parity oracle."""
    cands = [a for a in proposed if is_preemptible(a, current_priority)]
    cands.sort(key=lambda a: (a.job.priority, a.id))
    return cands


def victim_holds_exact_resources(alloc: Allocation) -> bool:
    """True when evicting this alloc changes state the dense resource
    columns can't model — reserved/dynamic port numbers or concrete
    device instances. The preempt_solve kernel flags any row whose
    victim set includes such an alloc so the placer re-routes that one
    request through the exact host scanner (preempt_for_network /
    preempt_for_device semantics)."""
    return bool(alloc.allocated_ports) or bool(alloc.allocated_devices)


def basic_resource_distance(need: np.ndarray, have: np.ndarray) -> float:
    """Euclidean distance between normalized resource vectors
    (reference preemption.go basicResourceDistance)."""
    d = 0.0
    for i in range(RESOURCE_DIMS):
        if need[i] > 0:
            d += ((have[i] - need[i]) / need[i]) ** 2
    return float(np.sqrt(d))


def _max_parallel_penalty(alloc: Allocation, counts: Dict[tuple, int]) -> float:
    """Score penalty once a victim's task group is at its migrate
    max_parallel in this selection (reference scoreForTaskGroup)."""
    job = alloc.job
    if job is None:
        return 0.0
    tg = job.lookup_task_group(alloc.task_group)
    if tg is None or tg.migrate is None:
        return 0.0
    max_parallel = tg.migrate.max_parallel
    if max_parallel <= 0:
        return 0.0
    n = counts.get((alloc.namespace, alloc.job_id, alloc.task_group), 0)
    if n < max_parallel:
        return 0.0
    return float((n + 1) - max_parallel) * MAX_PARALLEL_PENALTY


def preempt_for_task_group(
    node,
    proposed: Sequence[Allocation],
    ask_vec: np.ndarray,
    current_priority: int,
    check_devices: bool = False,
    ask_devices=(),
    preempted_counts: Optional[Dict[tuple, int]] = None,
) -> Optional[List[Allocation]]:
    """Pick a minimal set of lower-priority allocs whose removal lets the
    ask fit (reference preemption.go:127 PreemptForTaskGroup). Returns
    None/empty when impossible. `preempted_counts` carries per-(ns, job,
    tg) evictions already in the plan so migrate max_parallel penalties
    apply across the whole eval."""
    # shared eligibility + canonical priority-ascending order; within a
    # group the loop below prefers the alloc whose resources best match
    # what's still missing (smallest distance to need, plus the
    # max_parallel penalty)
    candidates = victim_candidates(proposed, current_priority)
    if not candidates:
        return None

    counts: Dict[tuple, int] = dict(preempted_counts or {})

    victims: List[Allocation] = []
    victim_ids = set()

    placement = Allocation(
        id="_cand", allocated_vec=ask_vec,
        allocated_devices={d.name: ["?"] * d.count for d in ask_devices}
        if check_devices else {})

    def fits_now() -> bool:
        remaining = [a for a in proposed if a.id not in victim_ids]
        fit, _, _ = allocs_fit(node, remaining + [placement],
                               check_devices=check_devices)
        return fit

    if fits_now():
        return None  # nothing to preempt; caller shouldn't have asked

    # iterate priority groups from lowest
    i = 0
    while i < len(candidates):
        prio = candidates[i].job.priority
        group = []
        while i < len(candidates) and candidates[i].job.priority == prio:
            group.append(candidates[i])
            i += 1
        # within the group, repeatedly take the best-matching alloc
        while group:
            # distance to the *remaining* need
            used = np.zeros(RESOURCE_DIMS)
            for a in proposed:
                if a.id not in victim_ids and a.should_count_for_usage():
                    used += a.allocated_vec
            need = used + ask_vec - node.available_vec()
            need = np.maximum(need, 0.0)
            group.sort(key=lambda a: (
                basic_resource_distance(need, a.allocated_vec)
                + _max_parallel_penalty(a, counts)))
            pick = group.pop(0)
            victims.append(pick)
            victim_ids.add(pick.id)
            ckey = (pick.namespace, pick.job_id, pick.task_group)
            counts[ckey] = counts.get(ckey, 0) + 1
            if fits_now():
                # drop any victim that is no longer necessary (reference
                # filterSuperset behavior: remove redundant evictions)
                for v in sorted(victims, key=lambda a: -a.job.priority):
                    victim_ids.discard(v.id)
                    if not fits_now():
                        victim_ids.add(v.id)
                return [v for v in victims if v.id in victim_ids]
    return None


def preempt_for_network(
    node,
    proposed: Sequence[Allocation],
    ask,
    current_priority: int,
    preempted_counts: Optional[Dict[tuple, int]] = None,
) -> Optional[List[Allocation]]:
    """Free conflicting reserved ports (reference preemption.go:30
    PreemptForNetwork). The reference also preempts on bandwidth
    (networkResourceDistance over mbits); this model's allocations
    record ports but not per-alloc bandwidth, so the network dimension
    here is reserved-port conflicts — victims are taken in ascending
    priority groups, direct holders of a needed port first, with the
    migrate max_parallel penalty applied (scoreForNetwork)."""
    needed_ports = {p[1] for p in ask.reserved_port_asks()}
    if not needed_ports:
        return None

    counts: Dict[tuple, int] = dict(preempted_counts or {})

    def alloc_ports(a: Allocation) -> set:
        return {p.value for p in a.allocated_ports}

    candidates = [a for a in proposed if is_preemptible(a, current_priority)
                  and alloc_ports(a) & needed_ports]
    if not candidates:
        return None

    victims: List[Allocation] = []
    victim_ids = set()

    def satisfied() -> bool:
        for a in proposed:
            if a.id in victim_ids or not a.should_count_for_usage():
                continue
            if alloc_ports(a) & needed_ports:
                return False
        return True

    if satisfied():
        return None

    candidates.sort(key=lambda a: a.job.priority)
    i = 0
    while i < len(candidates):
        prio = candidates[i].job.priority
        group = []
        while i < len(candidates) and candidates[i].job.priority == prio:
            group.append(candidates[i])
            i += 1
        while group:
            group.sort(key=lambda a: (
                -len(alloc_ports(a) & needed_ports)
                + _max_parallel_penalty(a, counts)))
            pick = group.pop(0)
            victims.append(pick)
            victim_ids.add(pick.id)
            ckey = (pick.namespace, pick.job_id, pick.task_group)
            counts[ckey] = counts.get(ckey, 0) + 1
            if satisfied():
                return victims
    return None


def preempt_for_device(
    node,
    proposed: Sequence[Allocation],
    ask_devices,
    current_priority: int,
) -> Optional[List[Allocation]]:
    """Free device-group instances (reference preemption.go:16
    PreemptForDevice + selectBestAllocs): per unsatisfied ask, victims
    come from ascending priority groups, largest instance holders first,
    until enough instances are free."""
    from .devices import matching_groups

    victims: List[Allocation] = []
    victim_ids = set()

    for ask in ask_devices:
        groups = matching_groups(node, ask, {}, {})
        group_ids = {g.id for g in groups}
        capacity = sum(len(g.instance_ids) for g in groups)

        def held_instances(a: Allocation) -> int:
            return sum(len(inst)
                       for name, inst in (a.allocated_devices or {}).items()
                       if name in group_ids)

        def free_now() -> int:
            used = 0
            for a in proposed:
                if a.id in victim_ids or not a.should_count_for_usage():
                    continue
                used += held_instances(a)
            return capacity - used

        needed = ask.count - free_now()
        if needed <= 0:
            continue
        candidates = [a for a in proposed
                      if is_preemptible(a, current_priority)
                      and held_instances(a) > 0]
        if not candidates:
            return None
        # ascending priority, then largest holders first within a group
        # (reference selectBestAllocs sorts descending by instance count)
        candidates.sort(key=lambda a: (a.job.priority, -held_instances(a)))
        freed = 0
        for a in candidates:
            if freed >= needed:
                break
            victims.append(a)
            victim_ids.add(a.id)
            freed += held_instances(a)
        if freed < needed:
            return None
    return victims or None
