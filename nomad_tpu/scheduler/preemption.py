"""Preemption victim selection (reference scheduler/preemption.go, 779 LoC).

Implements the reference's core heuristic: only allocations of strictly
lower job priority are evictable; candidates are considered in ascending
priority groups and chosen by resource distance (how closely the victim's
resources match the remaining need, preemption.go basicResourceDistance),
stopping as soon as the ask fits.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..structs import allocs_fit
from ..structs.alloc import Allocation
from ..structs.resources import RESOURCE_DIMS


def basic_resource_distance(need: np.ndarray, have: np.ndarray) -> float:
    """Euclidean distance between normalized resource vectors
    (reference preemption.go basicResourceDistance)."""
    d = 0.0
    for i in range(RESOURCE_DIMS):
        if need[i] > 0:
            d += ((have[i] - need[i]) / need[i]) ** 2
    return float(np.sqrt(d))


def preempt_for_task_group(
    node,
    proposed: Sequence[Allocation],
    ask_vec: np.ndarray,
    current_priority: int,
    check_devices: bool = False,
    ask_devices=(),
) -> Optional[List[Allocation]]:
    """Pick a minimal set of lower-priority allocs whose removal lets the
    ask fit (reference preemption.go:127 PreemptForTaskGroup). Returns
    None/empty when impossible."""
    candidates = [
        a for a in proposed
        if a.job is not None and a.job.priority < current_priority
        and a.should_count_for_usage()
    ]
    if not candidates:
        return None

    # group by priority ascending; within a group prefer the alloc whose
    # resources best match what's still missing (smallest distance to need)
    candidates.sort(key=lambda a: (a.job.priority,))

    victims: List[Allocation] = []
    victim_ids = set()

    placement = Allocation(
        id="_cand", allocated_vec=ask_vec,
        allocated_devices={d.name: ["?"] * d.count for d in ask_devices}
        if check_devices else {})

    def fits_now() -> bool:
        remaining = [a for a in proposed if a.id not in victim_ids]
        fit, _, _ = allocs_fit(node, remaining + [placement],
                               check_devices=check_devices)
        return fit

    if fits_now():
        return None  # nothing to preempt; caller shouldn't have asked

    # iterate priority groups from lowest
    i = 0
    while i < len(candidates):
        prio = candidates[i].job.priority
        group = []
        while i < len(candidates) and candidates[i].job.priority == prio:
            group.append(candidates[i])
            i += 1
        # within the group, repeatedly take the best-matching alloc
        while group:
            # distance to the *remaining* need
            used = np.zeros(RESOURCE_DIMS)
            for a in proposed:
                if a.id not in victim_ids and a.should_count_for_usage():
                    used += a.allocated_vec
            need = used + ask_vec - node.available_vec()
            need = np.maximum(need, 0.0)
            group.sort(key=lambda a: basic_resource_distance(need, a.allocated_vec))
            pick = group.pop(0)
            victims.append(pick)
            victim_ids.add(pick.id)
            if fits_now():
                # drop any victim that is no longer necessary (reference
                # filterSuperset behavior: remove redundant evictions)
                for v in sorted(victims, key=lambda a: -a.job.priority):
                    victim_ids.discard(v.id)
                    if not fits_now():
                        victim_ids.add(v.id)
                return [v for v in victims if v.id in victim_ids]
    return None
