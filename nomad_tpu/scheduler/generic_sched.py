"""Service + batch scheduler (reference scheduler/generic_sched.go, 945 LoC).

Retry loop: reconcile -> place -> submit plan -> on partial commit refresh
snapshot and retry (<=5 attempts service / 2 batch); unplaceable allocs
produce/refresh a blocked evaluation (reference generic_sched.go:149-356).
"""

from __future__ import annotations

import copy as _copy
import time
from typing import List, Optional

from ..structs import enums
from ..structs.alloc import Allocation, RescheduleEvent, RescheduleTracker
from ..structs.evaluation import Evaluation
from ..utils import generate_uuid, generate_uuids
from .context import EvalContext
from .placer import HostPlacer, placer_for_algorithm
from .reconcile import AllocReconciler, PlacementRequest
from .util import tainted_nodes, update_non_terminal_allocs_to_lost

MAX_SERVICE_ATTEMPTS = 5  # reference generic_sched.go:94
MAX_BATCH_ATTEMPTS = 2

BLOCKED_EVAL_MAX_PLAN_DESC = "created due to placement conflicts"
BLOCKED_EVAL_FAILED_PLACEMENT_DESC = "created to place remaining allocations"


class GenericScheduler:
    def __init__(self, state, planner, *, batch: bool = False,
                 sched_config=None, logger=None, placer=None, on_event=None,
                 shared_caches=None):
        self.state = state            # a StateSnapshot-like view
        self.planner = planner
        self.batch = batch
        self.sched_config = sched_config
        self.logger = logger
        self.on_event = on_event
        # cross-eval constraint caches (see NewScheduler); None = per-eval
        self.shared_caches = shared_caches
        algorithm = (sched_config.scheduler_algorithm
                     if sched_config is not None else enums.SCHED_ALG_BINPACK)
        self._placer_injected = placer is not None
        self._base_algorithm = algorithm
        self.placer = placer if placer is not None else placer_for_algorithm(algorithm)
        self.max_attempts = MAX_BATCH_ATTEMPTS if batch else MAX_SERVICE_ATTEMPTS

        self.eval: Optional[Evaluation] = None
        self.plan = None
        self.deployment = None
        self.failed_tg_allocs = {}
        self.queued_allocs = {}
        self.blocked: Optional[Evaluation] = None
        self.followups: List[Evaluation] = []

    # -- Scheduler interface --

    def process(self, evaluation: Evaluation) -> None:
        self.eval = evaluation
        try:
            self._process_with_retries()
        except Exception as e:  # reference recovers panics into failed evals
            if self.logger:
                self.logger.exception("scheduler panic")
            self._set_status(enums.EVAL_STATUS_FAILED, str(e))
            raise

    # -- core loop --

    def _process_with_retries(self) -> None:
        # the attempt budget only counts *zero-progress* retries: a partial
        # commit resets it (reference scheduler/util.go retryMax's
        # progressMade callback, generic_sched.go:149) — under worker
        # contention every plan can be partially rejected many times in a
        # row while still converging, and that must not exhaust the eval
        attempt = 0
        fruitless = 0
        while fruitless < self.max_attempts:
            self._progress = False
            if self._attempt(attempt):
                return
            attempt += 1
            fruitless = 0 if self._progress else fruitless + 1
        # exceeded plan attempts: fail this eval but queue a blocked eval
        # so the work is not lost (reference generic_sched.go:151-170)
        self._create_blocked_eval(max_plan=True)
        self._set_status(enums.EVAL_STATUS_FAILED, "maximum attempts reached")

    def _attempt(self, attempt: int) -> bool:
        ev = self.eval
        self.failed_tg_allocs = {}
        self.queued_allocs = {}
        self.followups = []
        job = self.state.job_by_id(ev.job_id, ev.namespace)
        self.plan = ev.make_plan(job)
        ctx = EvalContext(self.state, self.plan, eval_id=ev.id, logger=self.logger,
                          on_event=self.on_event)
        if self.shared_caches is not None:
            ctx.regex_cache = self.shared_caches.setdefault("regex", {})
            ctx.version_cache = self.shared_caches.setdefault("version", {})
        if job is not None:
            ctx.eligibility.set_job(job)

        all_allocs = self.state.allocs_by_job(ev.job_id, ev.namespace)
        tainted = tainted_nodes(self.state, all_allocs)
        update_non_terminal_allocs_to_lost(self.plan, tainted, all_allocs)

        latest_dep = (self.state.latest_deployment_by_job(ev.job_id, ev.namespace)
                      if not self.batch else None)
        reconciler = AllocReconciler(
            job if (job is not None and not job.stopped()) else None,
            ev.job_id, all_allocs, tainted, batch=self.batch, eval_id=ev.id,
            deployment=latest_dep)
        results = reconciler.compute()
        # per-TG desired-update annotations, surfaced by the dry-run plan
        # endpoint (reference scheduler/annotate.go:42 Annotate)
        self.annotations = dict(results.desired_tg_updates)

        # deployments track service-job rollouts (reference reconcile.go
        # computeDeployments; watched by nomad/deploymentwatcher). A new
        # job version with an update stanza opens a new deployment.
        self.deployment = None
        if not self.batch and job is not None and not job.stopped():
            latest = self.state.latest_deployment_by_job(ev.job_id, ev.namespace)
            has_update = any(tg.update is not None for tg in job.task_groups)
            changes = results.total_places() > 0
            # a new deployment only for a job version that never had one —
            # a terminal deployment for the current version must NOT be
            # re-opened by later placements (drains, reschedules), or a
            # plain node drain could stall-fail-and-revert the job
            if has_update and changes and (
                    latest is None or latest.job_version != job.version):
                from ..structs.deployment import Deployment, DeploymentState

                dep = Deployment(
                    id=generate_uuid(),
                    namespace=job.namespace,
                    job_id=job.id,
                    job_version=job.version,
                    eval_priority=ev.priority,
                )
                now0 = time.time()
                for tg in job.task_groups:
                    if tg.update is None:
                        continue
                    # groups whose update is entirely in-place (or a
                    # no-op) have nothing to health-track; a deployment
                    # state for them would sit at 0 placements until the
                    # progress deadline failed it
                    tgr = results.groups.get(tg.name)
                    if tgr is None or not (tgr.place or tgr.destructive_update):
                        continue
                    # canaries only apply to UPDATE rollouts: the deployment
                    # demands canaries iff the reconciler actually asked for
                    # canary placements this eval. Initial versions and
                    # rollouts whose old allocs are all lost (replaced
                    # outright) must not, or the canary hold would fire on
                    # every later eval and stall a fully-placed rollout
                    # (reference reconcile.go requireCanary)
                    wants_canaries = any(p.canary for p in tgr.place)
                    dep.task_groups[tg.name] = DeploymentState(
                        auto_revert=tg.update.auto_revert,
                        auto_promote=tg.update.auto_promote,
                        desired_canaries=tg.update.canary if wants_canaries else 0,
                        desired_total=tg.count,
                        progress_deadline_s=tg.update.progress_deadline_s,
                        require_progress_by=now0 + tg.update.progress_deadline_s,
                    )
                if dep.task_groups:
                    self.deployment = dep
                    self.plan.deployment = dep
            elif latest is not None and latest.active() \
                    and latest.job_version == job.version:
                self.deployment = latest

        # plan stops
        for tg_name, g in results.groups.items():
            for alloc, desc, client_status in g.stop:
                self.plan.append_stopped_alloc(alloc, desc, client_status)
            for alloc in g.destructive_update:
                self.plan.append_stopped_alloc(
                    alloc, "alloc is being updated due to job update")
            # in-place updates: same alloc, same node, same resources —
            # only the job definition it runs under advances (reference
            # scheduler/util.go genericAllocUpdateFn's in-place arm).
            # They join the active deployment so a mixed in-place/
            # destructive rollout can still reach the watcher's
            # "desired_total tracked allocs" completion bar; their
            # carried health keeps counting.
            tg_obj = job.lookup_task_group(tg_name) if job else None
            for alloc in g.inplace_update:
                upd = alloc.copy_for_update()
                upd.job = job
                upd.job_version = job.version
                if (self.deployment is not None and tg_obj is not None
                        and tg_obj.update is not None
                        and tg_name in self.deployment.task_groups):
                    upd.deployment_id = self.deployment.id
                self.plan.node_allocation.setdefault(
                    upd.node_id, []).append(upd)
            self.followups.extend(g.followup_evals)
            # annotate failed-then-delayed allocs with their followup eval
            for alloc_id, feval_id in g.delayed_reschedule.items():
                orig = next((a for a in all_allocs if a.id == alloc_id), None)
                if orig is not None:
                    upd = orig.copy_for_update()
                    upd.follow_up_eval_id = feval_id
                    self.plan.node_allocation.setdefault(upd.node_id, []).append(upd)
            # disconnecting allocs go client=unknown in the plan, tagged
            # with their max-disconnect-timeout eval (reference
            # plan AppendUnknownAlloc; reconcile.go disconnect updates)
            for alloc in g.disconnecting:
                upd = alloc.copy_for_update()
                upd.client_status = enums.ALLOC_CLIENT_UNKNOWN
                upd.client_description = "client disconnected"
                upd.follow_up_eval_id = g.disconnect_updates.get(alloc.id, "")
                self.plan.node_allocation.setdefault(upd.node_id, []).append(upd)

        # build placement request list (destructive updates also re-place)
        requests: List[PlacementRequest] = []
        job_obj = job
        for tg_name, g in results.groups.items():
            tg = job_obj.lookup_task_group(tg_name) if job_obj else None
            for alloc in g.destructive_update:
                requests.append(PlacementRequest(
                    name=alloc.name, task_group=tg, previous_alloc=alloc))
            requests.extend(g.place)
            if g.bulk_place is not None:
                requests.append(g.bulk_place)

        if requests and job_obj is not None:
            self._compute_placements(ctx, job_obj, requests, attempt)

        # no-op plan with nothing failed: done
        if self.plan.is_no_op() and not self.failed_tg_allocs:
            self._finish_success()
            return True

        # submit; the planner runs plan.post_apply_hooks synchronously
        # with its commit (core/plan_apply.py _commit, testing.py
        # Harness.submit_plan) so the solver-service ledger closes in
        # lockstep with the store write
        result, new_state = self.planner.submit_plan(self.plan)
        self._progress = bool(result.node_allocation or result.node_update
                              or result.node_preemptions or result.alloc_blocks
                              or result.deployment is not None)
        if new_state is not None:
            # partial commit: retry against fresher state
            self.state = new_state
            full, expected, actual = result.full_commit(self.plan)
            if not full:
                return False

        self._finish_success()
        return True

    def _compute_placements(self, ctx: EvalContext, job, requests, attempt: int) -> None:
        ev = self.eval
        nodes = self.state.ready_nodes_in_pool(job.datacenters, job.node_pool)
        # per-node-pool scheduler-config overrides (reference
        # generic_sched.go:737-752 applying SchedulerConfig.WithNodePool)
        effective = self.sched_config
        placer = self.placer
        if effective is not None:
            pool_fn = getattr(self.state, "node_pool", None)
            pool = pool_fn(job.node_pool) if pool_fn is not None else None
            effective = effective.with_node_pool(pool)
            if (not self._placer_injected
                    and effective.scheduler_algorithm != self._base_algorithm):
                placer = placer_for_algorithm(effective.scheduler_algorithm)
        preemption_enabled = (
            effective.preemption_enabled_for(job.type)
            if effective is not None else False)

        now = time.time()

        def commit(req, option):
            tg = req.task_group
            if option is None:
                # failed placement: coalesce per task group
                m = ctx.metrics
                prev = self.failed_tg_allocs.get(tg.name)
                if prev is None:
                    self.failed_tg_allocs[tg.name] = m
                else:
                    prev.coalesced_failures += 1
                self.queued_allocs[tg.name] = self.queued_allocs.get(tg.name, 0)
                return

            alloc = Allocation(
                id=generate_uuid(),
                eval_id=ev.id,
                deployment_id=(self.deployment.id
                               if self.deployment is not None
                               and tg.update is not None else ""),
                name=req.name,
                namespace=job.namespace,
                node_id=option.node.id,
                node_name=option.node.name,
                job_id=job.id,
                job=job,
                job_version=job.version,
                task_group=tg.name,
                allocated_vec=ctx.tg_vec(tg),
                allocated_ports=list(option.allocated_ports),
                allocated_devices=dict(option.allocated_devices),
                allocated_cores=list(option.allocated_cores),
                desired_status=enums.ALLOC_DESIRED_RUN,
                client_status=enums.ALLOC_CLIENT_PENDING,
                metrics=ctx.metrics,
                allocated_at=now,
            )
            if req.canary:
                alloc.canary = True
                if self.deployment is not None:
                    # record the placement on a plan-local deployment copy
                    # (the store row is shared MVCC state)
                    if self.plan.deployment is not self.deployment:
                        self.deployment = _copy.deepcopy(self.deployment)
                        self.plan.deployment = self.deployment
                    ds = self.deployment.task_groups.get(tg.name)
                    if ds is not None:
                        ds.placed_canaries = list(ds.placed_canaries) + [alloc.id]
            if req.previous_alloc is not None:
                prev = req.previous_alloc
                alloc.previous_allocation = prev.id
                if req.reschedule:
                    tracker = RescheduleTracker(
                        events=list(prev.reschedule_tracker.events)
                        if prev.reschedule_tracker else [])
                    tracker.events.append(RescheduleEvent(
                        reschedule_time=now, prev_alloc_id=prev.id,
                        prev_node_id=prev.node_id))
                    alloc.reschedule_tracker = tracker
                    # link old -> new
                    upd = prev.copy_for_update()
                    upd.next_allocation = alloc.id
                    self.plan.node_allocation.setdefault(upd.node_id, []).append(upd)
            if option.preempted_allocs:
                for victim in option.preempted_allocs:
                    self.plan.append_preempted_alloc(victim, alloc.id)
            self.plan.append_alloc(alloc)
            self.queued_allocs[tg.name] = self.queued_allocs.get(tg.name, 0) + 1

        def commit_many(tg, node, reqs, mean_score):
            """Bulk fast path: semantically the `commit(req, option)`
            success arm specialized to fresh placements (no canary, no
            previous_alloc, no ports/devices/cores — the placer's bulk
            eligibility), with the per-request constants hoisted out of
            the loop."""
            dep_id = (self.deployment.id
                      if self.deployment is not None
                      and tg.update is not None else "")
            vec = ctx.tg_vec(tg)
            bucket = self.plan.node_allocation.setdefault(node.id, [])
            tg_name = tg.name
            node_id, node_name = node.id, node.name
            metrics = ctx.metrics
            if metrics is not None:
                metrics.scores.setdefault("bulk.normalized-score", mean_score)
            ids = generate_uuids(len(reqs))
            for req, aid in zip(reqs, ids):
                bucket.append(Allocation(
                    id=aid,
                    eval_id=ev.id,
                    deployment_id=dep_id,
                    name=req.name,
                    namespace=job.namespace,
                    node_id=node_id,
                    node_name=node_name,
                    job_id=job.id,
                    job=job,
                    job_version=job.version,
                    task_group=tg_name,
                    allocated_vec=vec,
                    desired_status=enums.ALLOC_DESIRED_RUN,
                    client_status=enums.ALLOC_CLIENT_PENDING,
                    metrics=metrics,
                    allocated_at=now,
                ))
            self.queued_allocs[tg_name] = (
                self.queued_allocs.get(tg_name, 0) + len(reqs))

        def commit_block(tg, node_ids, node_names, counts, name_indices,
                         mean_score):
            """Columnar bulk commit: ONE AllocBlock rides the plan for K
            placements (structs/alloc.py AllocBlock). Only reachable for
            the fresh-placement shape commit_many covers, so the same
            constants apply; per-alloc ids/names materialize lazily."""
            from ..structs.alloc import AllocBlock

            block = AllocBlock(
                id=generate_uuid(),
                eval_id=ev.id,
                namespace=job.namespace,
                job_id=job.id,
                job=job,
                job_version=job.version,
                task_group=tg.name,
                deployment_id=(self.deployment.id
                               if self.deployment is not None
                               and tg.update is not None else ""),
                name_indices=name_indices,
                node_ids=list(node_ids),
                node_names=list(node_names),
                counts=counts,
                allocated_vec=ctx.tg_vec(tg),
                mean_score=float(mean_score),
                allocated_at=now,
            )
            metrics = ctx.metrics
            if metrics is not None:
                metrics.scores.setdefault("bulk.normalized-score",
                                          float(mean_score))
            self.plan.append_block(block)
            self.queued_allocs[tg.name] = (
                self.queued_allocs.get(tg.name, 0) + block.size)

        def fail_bulk(tg, n):
            """Coalesced failure accounting for n unplaced bulk requests
            (reference generic_sched.go:563-567 CoalescedFailures)."""
            if n <= 0:
                return
            m = ctx.metrics
            prev = self.failed_tg_allocs.get(tg.name)
            if prev is None:
                m.coalesced_failures += n - 1
                self.failed_tg_allocs[tg.name] = m
            else:
                prev.coalesced_failures += n
            self.queued_allocs.setdefault(tg.name, 0)

        commit.commit_many = commit_many
        commit.commit_block = commit_block
        commit.fail_bulk = fail_bulk
        placer.place(
            ctx, job, requests, nodes, commit,
            batch=self.batch, preemption_enabled=preemption_enabled,
            attempt=attempt)

    # -- eval bookkeeping --

    def _finish_success(self) -> None:
        for f in self.followups:
            self.planner.create_eval(f)
        if self.failed_tg_allocs:
            self._create_blocked_eval(max_plan=False)
            self._set_status(enums.EVAL_STATUS_COMPLETE,
                             "complete with failed placements")
        else:
            self._set_status(enums.EVAL_STATUS_COMPLETE, "")

    def _create_blocked_eval(self, max_plan: bool) -> None:
        ev = self.eval
        if ev.status == enums.EVAL_STATUS_BLOCKED or ev.triggered_by == enums.TRIGGER_QUEUED_ALLOCS:
            # this eval IS a blocked eval being retried: reblock it
            reblocked = _copy.copy(ev)
            reblocked.status = enums.EVAL_STATUS_BLOCKED
            self.planner.reblock_eval(reblocked)
            self.blocked = reblocked
            return
        blocked = Evaluation(
            id=generate_uuid(),
            namespace=ev.namespace,
            priority=ev.priority,
            type=ev.type,
            triggered_by=enums.TRIGGER_MAX_PLANS if max_plan else enums.TRIGGER_QUEUED_ALLOCS,
            job_id=ev.job_id,
            status=enums.EVAL_STATUS_BLOCKED,
            status_description=(BLOCKED_EVAL_MAX_PLAN_DESC if max_plan
                                else BLOCKED_EVAL_FAILED_PLACEMENT_DESC),
            previous_eval=ev.id,
        )
        # class eligibility lets the blocked-evals tracker unblock cheaply
        # (reference generic_sched.go:225 createBlockedEval)
        self.planner.create_eval(blocked)
        self.blocked = blocked

    def _set_status(self, status: str, desc: str) -> None:
        ev = _copy.copy(self.eval)
        ev.status = status
        ev.status_description = desc
        ev.failed_tg_allocs = self.failed_tg_allocs
        ev.queued_allocations = dict(self.queued_allocs)
        if self.blocked is not None:
            ev.blocked_eval = self.blocked.id
        self.planner.update_eval(ev)
