"""Scheduler utilities (reference scheduler/util.go)."""

from __future__ import annotations

from typing import Dict, Iterable, List, Set

from ..structs import Node, enums
from ..structs.alloc import Allocation, alloc_name


def tainted_nodes(snapshot, allocs: Iterable[Allocation]) -> Dict[str, Node]:
    """Map of node id -> node for nodes that are draining, down, or
    disconnected — any alloc on them needs attention
    (reference scheduler/util.go:130 taintedNodes)."""
    out: Dict[str, Node] = {}
    seen = set()
    for alloc in allocs:
        if alloc.node_id in seen:
            continue
        seen.add(alloc.node_id)
        node = snapshot.node_by_id(alloc.node_id)
        if node is None:
            # node no longer exists: treat as tainted-down via a synthetic row
            out[alloc.node_id] = Node(id=alloc.node_id, status=enums.NODE_STATUS_DOWN)
            continue
        if node.drain or node.status in (enums.NODE_STATUS_DOWN, enums.NODE_STATUS_DISCONNECTED):
            out[node.id] = node
        elif node.scheduling_eligibility == enums.NODE_SCHED_INELIGIBLE:
            # ineligible nodes don't taint running allocs; skip
            continue
    return out


class AllocNameIndex:
    """Bitmap of in-use alloc name indexes for a task group, so new
    placements reuse the lowest free "<job>.<group>[i]" names
    (reference scheduler/reconcile_util.go:625 allocNameIndex)."""

    def __init__(self, job_id: str, group: str, count: int,
                 in_use: Iterable[Allocation] = ()):
        self.job_id = job_id
        self.group = group
        self.count = count
        self.used: Set[int] = set()
        for a in in_use:
            idx = a.index()
            if idx >= 0:
                self.used.add(idx)

    def next_batch(self, n: int) -> List[str]:
        """Hand out n names, preferring unused indexes < count, then
        unused beyond count."""
        out = []
        i = 0
        while len(out) < n:
            if i not in self.used:
                self.used.add(i)
                out.append(alloc_name(self.job_id, self.group, i))
            i += 1
        return out

    def release(self, name_index: int) -> None:
        self.used.discard(name_index)


def update_non_terminal_allocs_to_lost(plan, tainted: Dict[str, Node],
                                       allocs: Iterable[Allocation]) -> None:
    """Mark non-terminal allocs on down nodes as lost in the plan
    (reference scheduler/util.go:915 updateNonTerminalAllocsToLost)."""
    for alloc in allocs:
        node = tainted.get(alloc.node_id)
        if node is None:
            continue
        if node.status != enums.NODE_STATUS_DOWN:
            continue
        if alloc.server_terminal() or alloc.client_terminal():
            continue
        plan.append_stopped_alloc(alloc, "alloc lost since node is down",
                                  client_status=enums.ALLOC_CLIENT_LOST)
