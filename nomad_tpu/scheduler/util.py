"""Scheduler utilities (reference scheduler/util.go)."""

from __future__ import annotations

from typing import Dict, Iterable, List, Set

from ..structs import Node, enums
from ..structs.alloc import Allocation, alloc_name


def tainted_nodes(snapshot, allocs: Iterable[Allocation]) -> Dict[str, Node]:
    """Map of node id -> node for nodes that are draining, down, or
    disconnected — any alloc on them needs attention
    (reference scheduler/util.go:130 taintedNodes)."""
    out: Dict[str, Node] = {}
    seen = set()
    for alloc in allocs:
        if alloc.node_id in seen:
            continue
        seen.add(alloc.node_id)
        node = snapshot.node_by_id(alloc.node_id)
        if node is None:
            # node no longer exists: treat as tainted-down via a synthetic row
            out[alloc.node_id] = Node(id=alloc.node_id, status=enums.NODE_STATUS_DOWN)
            continue
        if node.drain or node.status in (enums.NODE_STATUS_DOWN, enums.NODE_STATUS_DISCONNECTED):
            out[node.id] = node
        elif node.scheduling_eligibility == enums.NODE_SCHED_INELIGIBLE:
            # ineligible nodes don't taint running allocs; skip
            continue
    return out


class AllocNameIndex:
    """Bitmap of in-use alloc name indexes for a task group, so new
    placements reuse the lowest free "<job>.<group>[i]" names
    (reference scheduler/reconcile_util.go:625 allocNameIndex)."""

    def __init__(self, job_id: str, group: str, count: int,
                 in_use: Iterable[Allocation] = ()):
        self.job_id = job_id
        self.group = group
        self.count = count
        self.used: Set[int] = set()
        for a in in_use:
            idx = a.index()
            if idx >= 0:
                self.used.add(idx)

    def next_batch(self, n: int) -> List[str]:
        """Hand out n names, preferring unused indexes < count, then
        unused beyond count."""
        out = []
        i = 0
        while len(out) < n:
            if i not in self.used:
                self.used.add(i)
                out.append(alloc_name(self.job_id, self.group, i))
            i += 1
        return out

    def next_batch_indices(self, n: int):
        """Hand out n name INDEXES as an array (the bulk/columnar path:
        no per-alloc string formatting; AllocBlock materializes names
        lazily)."""
        import numpy as np

        out = np.empty(n, dtype=np.int64)
        filled = 0
        if not self.used:
            # fresh group: indexes are simply 0..n-1
            out[:] = np.arange(n)
            self.used.update(range(n))
            return out
        i = 0
        while filled < n:
            if i not in self.used:
                self.used.add(i)
                out[filled] = i
                filled += 1
            i += 1
        return out

    def release(self, name_index: int) -> None:
        self.used.discard(name_index)


def update_non_terminal_allocs_to_lost(plan, tainted: Dict[str, Node],
                                       allocs: Iterable[Allocation]) -> None:
    """Mark non-terminal allocs on down nodes as lost in the plan
    (reference scheduler/util.go:915 updateNonTerminalAllocsToLost)."""
    for alloc in allocs:
        node = tainted.get(alloc.node_id)
        if node is None:
            continue
        if node.status != enums.NODE_STATUS_DOWN:
            continue
        if alloc.server_terminal() or alloc.client_terminal():
            continue
        plan.append_stopped_alloc(alloc, "alloc lost since node is down",
                                  client_status=enums.ALLOC_CLIENT_LOST)


def _network_sig(networks) -> list:
    return sorted(
        (n.mode or "host", tuple(sorted(n.reserved_ports)),
         tuple(sorted(n.dynamic_ports)))
        for n in networks)


def _device_sig(devices) -> list:
    from ..structs.wire import wire_encode

    return sorted(
        (d.name, d.count, repr(wire_encode(list(d.constraints))),
         repr(wire_encode(list(d.affinities))))
        for d in devices)


def tasks_updated(old_tg, new_tg) -> bool:
    """Whether a task-group spec change requires destroying and replacing
    its allocations (reference scheduler/util.go tasksUpdated). Changes
    that the client can apply to a running alloc — count, meta, update
    strategy, reschedule/restart policy, kill timeouts, service tags —
    are NOT destructive; anything touching what actually runs or what
    resources it holds is."""
    from ..structs.wire import wire_encode

    if old_tg is None or new_tg is None:
        return True
    # group-level: networks/ports, volumes, ephemeral disk
    if _network_sig(old_tg.networks) != _network_sig(new_tg.networks):
        return True
    if wire_encode(old_tg.volumes) != wire_encode(new_tg.volumes):
        return True
    if (old_tg.ephemeral_disk.size_mb != new_tg.ephemeral_disk.size_mb
            or old_tg.ephemeral_disk.migrate != new_tg.ephemeral_disk.migrate):
        return True
    # placement-shaping changes: the in-place path keeps the alloc on its
    # node WITHOUT re-running feasibility, so anything that could make
    # the current node infeasible (or badly scored) must be destructive.
    # (The reference instead re-checks feasibility in inplaceUpdate and
    # demotes to destructive on failure; forcing destructive here is the
    # conservative equivalent.)
    if (wire_encode(list(old_tg.constraints)) != wire_encode(list(new_tg.constraints))
            or wire_encode(list(old_tg.affinities)) != wire_encode(list(new_tg.affinities))
            or wire_encode(list(old_tg.spreads)) != wire_encode(list(new_tg.spreads))):
        return True
    olds = {t.name: t for t in old_tg.tasks}
    news = {t.name: t for t in new_tg.tasks}
    if set(olds) != set(news):
        return True
    for name, o in olds.items():
        n = news[name]
        if (o.driver != n.driver or o.user != n.user
                or o.config != n.config or o.env != n.env
                or o.artifacts != n.artifacts or o.templates != n.templates
                or o.lifecycle_hook != n.lifecycle_hook
                or o.lifecycle_sidecar != n.lifecycle_sidecar
                or o.leader != n.leader):
            return True
        if (wire_encode(list(o.constraints)) != wire_encode(list(n.constraints))
                or wire_encode(list(o.affinities)) != wire_encode(list(n.affinities))):
            return True
        orr, nrr = o.resources, n.resources
        if (orr.cpu != nrr.cpu or orr.memory_mb != nrr.memory_mb
                or orr.memory_max_mb != nrr.memory_max_mb
                or orr.disk_mb != nrr.disk_mb or orr.cores != nrr.cores
                or orr.numa_affinity != nrr.numa_affinity):
            return True
        if _network_sig(orr.networks) != _network_sig(nrr.networks):
            return True
        if _device_sig(orr.devices) != _device_sig(nrr.devices):
            return True
        if wire_encode(list(o.volume_mounts)) != wire_encode(list(n.volume_mounts)):
            return True
    return False
