"""Per-evaluation context (reference scheduler/context.go).

Carries the immutable state snapshot, the in-progress plan, parse caches
(regexp/version, reference context.go:15), the computed-class eligibility
memoizer (context.go:261 EvalEligibility), and per-placement metrics.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional

from ..structs import AllocMetric, Job, Node, Plan, TaskGroup
from ..structs import enums


class EvalEligibility:
    """Memoizes feasibility per computed node class so a 10k-node cluster
    with 20 classes does ~20 constraint evaluations, not 10k
    (reference context.go:261; escape semantics for unique-attr
    constraints per context.go:292-305)."""

    def __init__(self):
        self.job: Dict[str, bool] = {}       # class -> eligible at job level
        self.tg: Dict[str, Dict[str, bool]] = {}  # tg name -> class -> eligible
        self.job_escaped = False
        self.tg_escaped: Dict[str, bool] = {}

    def set_job(self, job: Job) -> None:
        from .feasible import is_class_escaped

        self.job_escaped = any(
            is_class_escaped(c.ltarget) or is_class_escaped(c.rtarget)
            for c in job.constraints
        )
        for tg in job.task_groups:
            constraints = list(tg.constraints)
            for t in tg.tasks:
                constraints.extend(t.constraints)
            self.tg_escaped[tg.name] = any(
                is_class_escaped(c.ltarget) or is_class_escaped(c.rtarget)
                for c in constraints
            )

    def job_status(self, klass: str) -> Optional[bool]:
        if self.job_escaped or not klass:
            return None
        return self.job.get(klass)

    def set_job_status(self, klass: str, eligible: bool) -> None:
        if not self.job_escaped and klass:
            self.job[klass] = eligible

    def tg_status(self, tg_name: str, klass: str) -> Optional[bool]:
        if self.tg_escaped.get(tg_name) or not klass:
            return None
        return self.tg.get(tg_name, {}).get(klass)

    def set_tg_status(self, tg_name: str, klass: str, eligible: bool) -> None:
        if not self.tg_escaped.get(tg_name) and klass:
            self.tg.setdefault(tg_name, {})[klass] = eligible


class EvalContext:
    """Reference scheduler/context.go EvalContext."""

    def __init__(self, snapshot, plan: Optional[Plan] = None, eval_id: str = "",
                 logger=None, on_event=None):
        self.snapshot = snapshot
        self.plan = plan
        self.eval_id = eval_id
        self.regex_cache: dict = {}
        self.version_cache: dict = {}
        self.eligibility = EvalEligibility()
        self.metrics: Optional[AllocMetric] = None
        self.logger = logger
        # domain-sanitizer sink, e.g. port collisions among committed
        # allocs (reference context.go:84 PortCollisionEvent via
        # SendEvent -> Server.listenWorkerEvents); the worker wires this
        # to the server's event broker
        self.on_event = on_event
        self._sent_events: set = set()
        self._tg_res: dict = {}
        self._tg_vec: dict = {}

    def tg_resources(self, tg: TaskGroup):
        """Per-eval memo of tg.combined_resources() — the combine walks
        every task and deep-copies networks, and the commit loop would
        otherwise pay it once per allocation."""
        r = self._tg_res.get(id(tg))
        if r is None:
            r = self._tg_res[id(tg)] = tg.combined_resources()
        return r

    def tg_vec(self, tg: TaskGroup):
        v = self._tg_vec.get(id(tg))
        if v is None:
            v = self._tg_vec[id(tg)] = self.tg_resources(tg).vec()
        return v

    def send_event(self, event: dict) -> None:
        key = repr(sorted(event.items()))
        if key in self._sent_events:
            return  # one emission per distinct event per eval
        self._sent_events.add(key)
        if self.logger:
            self.logger.warning("scheduler event: %s", event)
        if self.on_event is not None:
            self.on_event(dict(event, eval_id=self.eval_id))

    def new_metrics(self) -> AllocMetric:
        self.metrics = AllocMetric()
        return self.metrics

    def proposed_allocs(self, node_id: str) -> List:
        """The node's allocs as they would be if the in-progress plan
        committed: state minus evictions/preemptions plus placements
        (reference context.go:176 ProposedAllocs)."""
        existing = self.snapshot.allocs_by_node_terminal(node_id, False)
        if self.plan is None:
            return existing
        removed = set()
        for a in self.plan.node_update.get(node_id, ()):
            removed.add(a.id)
        for a in self.plan.node_preemptions.get(node_id, ()):
            removed.add(a.id)
        out = [a for a in existing if a.id not in removed]
        # placements may update an existing alloc in place (inplace update):
        placed_ids = {a.id for a in self.plan.node_allocation.get(node_id, ())}
        out = [a for a in out if a.id not in placed_ids]
        out.extend(self.plan.node_allocation.get(node_id, ()))
        return out

    def shuffled_nodes(self, nodes: List[Node], attempt: int = 0) -> List[Node]:
        """Deterministic shuffle seeded by eval id + retry attempt
        (reference scheduler/util.go:167 shuffleNodes, seeded by eval and
        plan-attempt index so retries explore different prefixes)."""
        rng = random.Random(f"{self.eval_id}:{attempt}")
        out = list(nodes)
        rng.shuffle(out)
        return out
