"""Spread scoring (reference scheduler/spread.go + propertyset.go).

Score boosts in [-1, 1] per spread attribute, weighted when explicit
targets exist, even-spread delta scoring otherwise.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..structs import Job, Node, Spread, TaskGroup
from .feasible import resolve_target

IMPLICIT_TARGET = "*"


def combined_spreads(job: Job, tg: TaskGroup) -> List[Spread]:
    return list(tg.spreads) + list(job.spreads)


class SpreadInfo:
    """Desired counts per attribute value (reference spread.go:268
    computeSpreadInfo): percent/100 * tg.count, remainder to "*"."""

    def __init__(self, spread: Spread, total_count: int):
        self.attribute = spread.attribute
        self.weight = spread.weight
        self.desired_counts: Dict[str, float] = {}
        total = 0.0
        for st in spread.targets:
            want = (st.percent / 100.0) * total_count
            self.desired_counts[st.value] = want
            total += want
        if 0 < total < total_count:
            self.desired_counts[IMPLICIT_TARGET] = total_count - total


class PropertySet:
    """Existing + proposed usage counts per value of one attribute for one
    task group (reference scheduler/propertyset.go)."""

    def __init__(self, attribute: str):
        self.attribute = attribute
        self.existing: Dict[str, int] = {}
        self.proposed: Dict[str, int] = {}
        self.cleared: Dict[str, int] = {}

    def populate_existing(self, allocs, node_by_id, tg_name: Optional[str] = None) -> None:
        for a in allocs:
            if a.terminal_status():
                continue
            if tg_name is not None and a.task_group != tg_name:
                continue
            node = node_by_id(a.node_id)
            if node is None:
                continue
            val, ok = resolve_target(self.attribute, node)
            if ok:
                self.existing[val] = self.existing.get(val, 0) + 1

    def add_proposed(self, node: Node) -> None:
        val, ok = resolve_target(self.attribute, node)
        if ok:
            self.proposed[val] = self.proposed.get(val, 0) + 1

    def remove_proposed(self, node: Node) -> None:
        val, ok = resolve_target(self.attribute, node)
        if ok and self.proposed.get(val, 0) > 0:
            self.proposed[val] -= 1

    def combined(self) -> Dict[str, int]:
        out = dict(self.existing)
        for k, v in self.proposed.items():
            out[k] = out.get(k, 0) + v
        for k, v in self.cleared.items():
            out[k] = max(0, out.get(k, 0) - v)
        return out

    def used_count(self, node: Node) -> Tuple[str, bool, int]:
        val, ok = resolve_target(self.attribute, node)
        if not ok:
            return val, False, 0
        return val, True, self.combined().get(val, 0)


def even_spread_boost(pset: PropertySet, node: Node) -> float:
    """Reference spread.go evenSpreadScoreBoost."""
    combined = pset.combined()
    if not combined:
        return 0.0
    val, ok = resolve_target(pset.attribute, node)
    if not ok:
        return -1.0
    current = combined.get(val, 0)
    counts = list(combined.values())
    min_count, max_count = min(counts), max(counts)
    if current != min_count:
        if min_count == 0:
            return -1.0
        return float(min_count - current) / float(min_count)
    if min_count == max_count:
        return -1.0
    if min_count == 0:
        return 1.0
    return float(max_count - min_count) / float(min_count)


class SpreadScorer:
    """Per-(job, tg) spread scoring state shared across the placements of
    one evaluation (property sets accumulate proposed placements)."""

    def __init__(self, job: Job, tg: TaskGroup, snapshot):
        self.spreads = combined_spreads(job, tg)
        self.infos: Dict[str, SpreadInfo] = {}
        self.psets: Dict[str, PropertySet] = {}
        self.sum_weights = 0.0
        self.lowest_boost = -1.0
        if not self.spreads:
            return
        existing = snapshot.allocs_by_job(job.id, job.namespace)
        for s in self.spreads:
            self.infos[s.attribute] = SpreadInfo(s, tg.count)
            self.sum_weights += abs(s.weight)
            pset = PropertySet(s.attribute)
            pset.populate_existing(existing, snapshot.node_by_id, tg.name)
            self.psets[s.attribute] = pset

    def has_spreads(self) -> bool:
        return bool(self.spreads)

    def score(self, node: Node) -> Optional[float]:
        """Total spread boost for placing on `node`, or None when no
        spreads / zero total (reference appends no sub-score then)."""
        if not self.spreads:
            return None
        total = 0.0
        for attr, pset in self.psets.items():
            val, ok, used = pset.used_count(node)
            used += 1  # include this placement
            if not ok:
                total -= 1.0
                continue
            info = self.infos[attr]
            if not info.desired_counts:
                total += even_spread_boost(pset, node)
                continue
            desired = info.desired_counts.get(val)
            if desired is None:
                desired = info.desired_counts.get(IMPLICIT_TARGET)
            if desired is None:
                total -= 1.0
                continue
            weight = info.weight / self.sum_weights if self.sum_weights else 0.0
            if desired == 0:
                total += self.lowest_boost
                continue
            boost = ((desired - used) / desired) * weight
            total += boost
            if boost < self.lowest_boost:
                self.lowest_boost = boost
        return total if total != 0.0 else None

    def record_placement(self, node: Node) -> None:
        for pset in self.psets.values():
            pset.add_proposed(node)
