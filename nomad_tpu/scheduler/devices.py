"""Device instance allocation + NUMA core selection.

Reference: scheduler/device.go:17 (deviceAllocator: fits RequestedDevice
against node device groups with constraint filtering + affinity scoring),
scheduler/numa_ce.go (coreSelector consumed at rank.go:510-525).

Split of responsibilities with the tensor path: the kernels fit device
and core *counts* as extra dense resource columns (tensor/cluster.py
appends them per task group); the concrete instance ids and core ids are
assigned here, host-side, per chosen node — the same post-solve pattern
ports use (structs/network.py NetworkIndex).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..structs import Node
from ..structs.resources import NodeDeviceResource, RequestedDevice
from .feasible import check_constraint


def resolve_device_target(target: str, group: NodeDeviceResource) -> Tuple[str, bool]:
    """Resolve "${device.*}" interpolation against one device group
    (reference structs/devices.go device constraint targets)."""
    if not target.startswith("${device."):
        return target, True  # literal
    key = target[len("${device."):-1]
    if key == "vendor":
        return group.vendor, True
    if key == "type":
        return group.type, True
    if key in ("model", "name"):
        return group.name, True
    if key.startswith("attr."):
        val = group.attributes.get(key[len("attr."):])
        return ("" if val is None else str(val)), val is not None
    return "", False


def group_meets_constraints(group: NodeDeviceResource, ask: RequestedDevice,
                            regex_cache=None, version_cache=None) -> bool:
    for c in ask.constraints:
        lval, lok = resolve_device_target(c.ltarget, group)
        rval, rok = resolve_device_target(c.rtarget, group)
        if not check_constraint(c.operand, lval, rval, lok, rok,
                                regex_cache, version_cache):
            return False
    return True


def matching_groups(node: Node, ask: RequestedDevice,
                    regex_cache=None, version_cache=None) -> List[NodeDeviceResource]:
    """Device groups satisfying the ask's selector and constraints."""
    return [g for g in node.resources.devices
            if g.matches(ask.name)
            and group_meets_constraints(g, ask, regex_cache, version_cache)]


def group_affinity_score(group: NodeDeviceResource, ask: RequestedDevice,
                         regex_cache=None, version_cache=None) -> float:
    """Normalized affinity score of one group for one ask
    (reference device.go createOffer affinity scoring)."""
    if not ask.affinities:
        return 0.0
    total, weights = 0.0, 0.0
    for aff in ask.affinities:
        weights += abs(aff.weight)
        lval, lok = resolve_device_target(aff.ltarget, group)
        rval, rok = resolve_device_target(aff.rtarget, group)
        if check_constraint(aff.operand, lval, rval, lok, rok,
                            regex_cache, version_cache):
            total += aff.weight
    return total / weights if weights else 0.0


def groups_capacity(groups: Sequence[NodeDeviceResource]) -> int:
    """Single definition of a device-group set's instance capacity — the
    kernel's count columns and the host DeviceIndex must agree on it."""
    return sum(len(g.instance_ids) for g in groups)


def device_capacity(node: Node, ask: RequestedDevice,
                    regex_cache=None, version_cache=None) -> int:
    """Total instances on the node that could serve this ask (usage-blind;
    usage rides the dense used column / DeviceIndex)."""
    return groups_capacity(matching_groups(node, ask, regex_cache, version_cache))


def accumulate_dev_usage(row: Dict[str, int], alloc, sign: int = 1) -> None:
    """Fold one alloc's device instances + reserved cores into a usage
    row ({device_group_id: n, "cores": n}) — the single definition of the
    row schema shared by the store's derived rows, snapshot restore, and
    the tensor layer's touched-node recompute."""
    for gid, instances in (alloc.allocated_devices or {}).items():
        row[gid] = row.get(gid, 0) + sign * len(instances)
    if alloc.allocated_cores:
        row["cores"] = row.get("cores", 0) + sign * len(alloc.allocated_cores)


class DeviceIndex:
    """Per-node instance bookkeeping for one placement pass: which
    concrete instances are taken by proposed allocs plus this group's
    earlier placements (reference device.go deviceAllocator state)."""

    def __init__(self, node: Node, proposed_allocs: Sequence = ()):
        self.node = node
        self.used: Dict[str, set] = {}
        for a in proposed_allocs:
            self.add_alloc(a)

    def add_alloc(self, alloc) -> None:
        for dev_id, instances in (alloc.allocated_devices or {}).items():
            self.used.setdefault(dev_id, set()).update(instances)

    def assign(self, asks: Sequence[RequestedDevice],
               regex_cache=None, version_cache=None) -> Optional[Dict[str, List[str]]]:
        """Pick concrete instances for every ask, preferring the
        highest-affinity group then the emptiest (spread within a node is
        irrelevant; the reference prefers score then fit). Returns
        {device group id: [instance ids]} or None; commits the picks into
        `used` only if the whole set assigns."""
        staged: Dict[str, List[str]] = {}
        staged_used: Dict[str, set] = {}
        for ask in asks:
            candidates = []
            for g in matching_groups(self.node, ask, regex_cache, version_cache):
                taken = self.used.get(g.id, set()) | staged_used.get(g.id, set())
                free = [i for i in g.instance_ids if i not in taken]
                if free:
                    score = group_affinity_score(g, ask, regex_cache, version_cache)
                    candidates.append((score, len(free), g, free))
            remaining = ask.count
            picks: List[Tuple[NodeDeviceResource, List[str]]] = []
            for score, _, g, free in sorted(
                    candidates, key=lambda c: (-c[0], -c[1], c[2].id)):
                take = free[:remaining]
                picks.append((g, take))
                remaining -= len(take)
                if remaining <= 0:
                    break
            if remaining > 0:
                return None
            for g, take in picks:
                staged.setdefault(g.id, []).extend(take)
                staged_used.setdefault(g.id, set()).update(take)
        for gid, instances in staged.items():
            self.used.setdefault(gid, set()).update(instances)
        return staged


def device_affinity_boost(node: Node, asks: Sequence[RequestedDevice],
                          regex_cache=None, version_cache=None) -> float:
    """Node-level device affinity sub-score: the best reachable group
    score per ask, averaged over asks that have affinities (feeds the
    rank normalizer next to node affinity; reference rank.go folds the
    deviceAllocator's offer score into the node score)."""
    total, n = 0.0, 0
    for ask in asks:
        if not ask.affinities:
            continue
        n += 1
        groups = matching_groups(node, ask, regex_cache, version_cache)
        if groups:
            total += max(group_affinity_score(g, ask, regex_cache, version_cache)
                         for g in groups)
    return total / n if n else 0.0


# ---------------------------------------------------------------------------
# NUMA-aware core selection (reference scheduler/numa_ce.go coreSelector)
# ---------------------------------------------------------------------------


def combined_numa_affinity(tg) -> str:
    """Strictest task policy wins when the group's asks are summed."""
    order = {"none": 0, "prefer": 1, "require": 2}
    best = "none"
    for t in tg.tasks:
        pol = t.resources.numa_affinity or "none"
        if order.get(pol, 0) > order[best]:
            best = pol
    return best


def used_cores(proposed_allocs: Sequence) -> set:
    out: set = set()
    for a in proposed_allocs:
        out.update(a.allocated_cores or ())
    return out


def select_cores(node: Node, proposed_allocs: Sequence, k: int,
                 numa_affinity: str = "none",
                 taken: Optional[set] = None) -> Optional[List[int]]:
    """Pick k free core ids. With NUMA topology: "require" means all k
    from a single domain (fail otherwise), "prefer" packs into as few
    domains as possible, "none" takes the lowest free ids. Packing picks
    the fullest-fitting domain first — binpack for cores, keeping big
    contiguous domains free (reference numa_ce.go is a CE stub that
    randomizes; the enterprise selector packs, and packing is strictly
    better for future require-asks). Callers tracking their own used-core
    set pass `taken` directly instead of the alloc list."""
    if k <= 0:
        return []
    if taken is None:
        taken = used_cores(proposed_allocs)
    domains = node.resources.numa
    if not domains:
        free = [c for c in range(int(node.resources.total_cores)) if c not in taken]
        return sorted(free)[:k] if len(free) >= k else None

    free_by_domain = []
    for d in domains:
        free = sorted(c for c in d.cores if c not in taken)
        free_by_domain.append((d.id, free))

    if numa_affinity == "require":
        fitting = [(len(f), did, f) for did, f in free_by_domain if len(f) >= k]
        if not fitting:
            return None
        _, _, free = min(fitting)  # tightest domain that fits
        return free[:k]

    total_free = sum(len(f) for _, f in free_by_domain)
    if total_free < k:
        return None
    if numa_affinity == "prefer":
        fitting = [(len(f), did, f) for did, f in free_by_domain if len(f) >= k]
        if fitting:
            _, _, free = min(fitting)
            return free[:k]
        # no single domain fits: drain domains fullest-first
        out: List[int] = []
        for _, _, free in sorted(((len(f), did, f) for did, f in free_by_domain)):
            out.extend(free[: k - len(out)])
            if len(out) == k:
                return out
        return None
    # "none": lowest ids across the node
    free = sorted(c for _, f in free_by_domain for c in f)
    return free[:k]
