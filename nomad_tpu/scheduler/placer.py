"""Placement backends.

The scheduler asks a Placer to choose nodes for a batch of placement
requests. Two implementations share this interface:

- HostPlacer: per-request greedy select (reference stack.go Select) —
  exact reference behavior;
- TPUPlacer (nomad_tpu.tensor.placer): lowers the whole request batch to
  dense tensors and solves placement as one fused JAX program. Selected
  via SchedulerAlgorithm="tpu-binpack".
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..structs import Job, Node, enums
from .context import EvalContext
from .rank import NodeScorer, RankedNode, select_best_node
from .reconcile import PlacementRequest


class HostPlacer:
    """Greedy per-placement selection — the reference semantics."""

    def __init__(self, algorithm: str = enums.SCHED_ALG_BINPACK):
        self.algorithm = algorithm

    def place(
        self,
        ctx: EvalContext,
        job: Job,
        requests: Sequence[PlacementRequest],
        nodes: Sequence[Node],
        commit,
        *,
        batch: bool = False,
        preemption_enabled: bool = False,
        attempt: int = 0,
    ) -> None:
        """Select a node for each request, calling ``commit(req, option)``
        immediately after each decision. The commit callback appends the
        alloc to the in-progress plan, which is how subsequent selections
        see earlier ones via ctx.proposed_allocs (the reference appends in
        the computePlacements loop, generic_sched.go:511-600)."""
        from .reconcile import BulkPlacementRequest

        # the host path has no columnar shape: expand bulk requests into
        # their per-alloc equivalents (exact reference semantics)
        if any(isinstance(r, BulkPlacementRequest) for r in requests):
            flat = []
            for r in requests:
                flat.extend(r.expand() if isinstance(r, BulkPlacementRequest)
                            else [r])
            requests = flat
        scorers: Dict[str, NodeScorer] = {}
        for req in requests:
            tg = req.task_group
            scorer = scorers.get(tg.name)
            if scorer is None:
                scorer = NodeScorer(ctx, job, tg, algorithm=self.algorithm,
                                    preemption_enabled=preemption_enabled)
                scorers[tg.name] = scorer
            penalty = frozenset({req.ignore_node}) if req.ignore_node else frozenset()
            option = select_best_node(
                ctx, job, tg, nodes,
                batch=batch,
                algorithm=self.algorithm,
                preemption_enabled=preemption_enabled,
                penalty_nodes=penalty,
                scorer=scorer,
                attempt=attempt,
            )
            if option is not None:
                scorer.record_placement(option.node)
            commit(req, option)


def placer_for_algorithm(algorithm: str):
    """Factory honoring SchedulerConfiguration.scheduler_algorithm."""
    if algorithm == enums.SCHED_ALG_TPU_BINPACK:
        from ..tensor.placer import TPUPlacer

        return TPUPlacer()
    if algorithm == enums.SCHED_ALG_TPU_SOLVE:
        # the global-batch tier: same TPUPlacer surface, but bulk solves
        # route to the joint auction kernel (tensor/batch_solver.py);
        # everything non-bulk degrades to the greedy/host fallback arms
        from ..tensor.placer import TPUPlacer

        return TPUPlacer(algorithm=enums.SCHED_ALG_TPU_SOLVE)
    return HostPlacer(algorithm=algorithm)
