"""Scheduler interfaces + factory (reference scheduler/scheduler.go:27-151).

`State` is any object with the StateSnapshot query surface; `Planner` is
how a scheduler submits plans and creates evals without knowing whether
it runs inside a test harness or a server worker.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Protocol

from ..structs import enums
from ..structs.evaluation import Evaluation
from ..structs.plan import Plan, PlanResult

SCHEDULER_VERSION = 1


class Planner(Protocol):
    """Reference scheduler/scheduler.go:126 Planner."""

    def submit_plan(self, plan: Plan) -> tuple:
        """-> (PlanResult, new_state_or_None). A non-None state means the
        plan was partially applied and the scheduler should retry against
        the fresher snapshot (reference worker.go:650 SubmitPlan)."""
        ...

    def update_eval(self, evaluation: Evaluation) -> None: ...

    def create_eval(self, evaluation: Evaluation) -> None: ...

    def reblock_eval(self, evaluation: Evaluation) -> None: ...


class Scheduler(Protocol):
    """Reference scheduler/scheduler.go:59."""

    def process(self, evaluation: Evaluation) -> None: ...


def NewScheduler(sched_type: str, state, planner: Planner, *,
                 sched_config=None, logger=None, placer=None,
                 on_event=None, shared_caches=None) -> "Scheduler":
    """Factory (reference scheduler/scheduler.go:36 NewScheduler).

    shared_caches: optional {"regex": {}, "version": {}} dicts seeded
    into every EvalContext this scheduler builds, so a worker processing
    a batch of evals compiles each constraint regex / parses each
    version string once per batch instead of once per eval. The caches
    are content-keyed (pattern -> compiled), so sharing across evals is
    always sound; the caller owns their thread-confinement."""
    factory = BUILTIN_SCHEDULERS.get(sched_type)
    if factory is None:
        raise ValueError(f"unknown scheduler type {sched_type!r}")
    return factory(state, planner, sched_config=sched_config, logger=logger,
                   placer=placer, on_event=on_event,
                   shared_caches=shared_caches)


def _make_registry():
    from .generic_sched import GenericScheduler
    from .system_sched import SystemScheduler

    return {
        enums.JOB_TYPE_SERVICE: lambda s, p, **kw: GenericScheduler(s, p, batch=False, **kw),
        enums.JOB_TYPE_BATCH: lambda s, p, **kw: GenericScheduler(s, p, batch=True, **kw),
        enums.JOB_TYPE_SYSTEM: lambda s, p, **kw: SystemScheduler(s, p, sysbatch=False, **kw),
        enums.JOB_TYPE_SYSBATCH: lambda s, p, **kw: SystemScheduler(s, p, sysbatch=True, **kw),
    }


class _LazyRegistry(dict):
    def __missing__(self, key):
        self.update(_make_registry())
        if key in self:
            return self[key]
        raise KeyError(key)

    def get(self, key, default=None):
        if not self:
            self.update(_make_registry())
        return super().get(key, default)


BUILTIN_SCHEDULERS: Dict[str, Callable] = _LazyRegistry()
