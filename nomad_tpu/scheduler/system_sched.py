"""System + sysbatch scheduler (reference scheduler/scheduler_system.go +
system_util.go): place one alloc of each task group on every feasible
node; diff-based, no reconciler.
"""

from __future__ import annotations

import copy as _copy
import time
from typing import Dict, List, Optional, Tuple

from ..structs import enums
from ..structs.alloc import Allocation, alloc_name
from ..structs.evaluation import Evaluation
from ..utils import generate_uuid
from .context import EvalContext
from .rank import NodeScorer, _class_feasible
from .util import tainted_nodes, update_non_terminal_allocs_to_lost


class SystemScheduler:
    def __init__(self, state, planner, *, sysbatch: bool = False,
                 sched_config=None, logger=None, placer=None, on_event=None,
                 shared_caches=None):
        self.state = state
        self.planner = planner
        self.sysbatch = sysbatch
        self.sched_config = sched_config
        self.logger = logger
        self.on_event = on_event
        # cross-eval constraint caches (see NewScheduler); None = per-eval
        self.shared_caches = shared_caches
        self.eval: Optional[Evaluation] = None
        self.plan = None
        self.failed_tg_allocs = {}
        self.queued_allocs = {}

    def process(self, evaluation: Evaluation) -> None:
        self.eval = evaluation
        for attempt in range(2):
            if self._attempt(attempt):
                return
        self._set_status(enums.EVAL_STATUS_FAILED, "maximum attempts reached")

    def _attempt(self, attempt: int) -> bool:
        ev = self.eval
        self.failed_tg_allocs = {}
        job = self.state.job_by_id(ev.job_id, ev.namespace)
        self.plan = ev.make_plan(job)
        ctx = EvalContext(self.state, self.plan, eval_id=ev.id, logger=self.logger,
                          on_event=self.on_event)
        if self.shared_caches is not None:
            ctx.regex_cache = self.shared_caches.setdefault("regex", {})
            ctx.version_cache = self.shared_caches.setdefault("version", {})

        all_allocs = self.state.allocs_by_job(ev.job_id, ev.namespace)
        tainted = tainted_nodes(self.state, all_allocs)
        update_non_terminal_allocs_to_lost(self.plan, tainted, all_allocs)

        stopped = job is None or job.stopped()
        nodes = ([] if stopped else
                 self.state.ready_nodes_in_pool(job.datacenters, job.node_pool))
        node_ids = {n.id for n in nodes}

        # existing live allocs keyed by (node, task group)
        # (reference system_util.go:299 diffSystemAllocs)
        live: Dict[Tuple[str, str], Allocation] = {}
        for a in all_allocs:
            if a.terminal_status():
                continue
            live[(a.node_id, a.task_group)] = a

        # stop allocs on nodes that are gone/ineligible or whose group vanished
        if job is not None:
            valid_groups = {tg.name for tg in job.task_groups}
        else:
            valid_groups = set()
        for (node_id, tg_name), a in live.items():
            if node_id in tainted:
                continue  # handled via lost/migrate path
            if stopped or tg_name not in valid_groups:
                self.plan.append_stopped_alloc(a, "alloc not needed")
                continue
            if node_id in node_ids:
                continue
            node = self.state.node_by_id(node_id)
            if node is not None and node.in_pool(job.datacenters, job.node_pool):
                # node exists in the job's DC/pool but is not ready (e.g.
                # marked scheduling-ineligible pre-maintenance):
                # ineligibility only blocks new placements, running allocs
                # stay (reference system_util.go:200 ignores allocs on
                # notReadyNodes instead of stopping them)
                continue
            self.plan.append_stopped_alloc(a, "alloc not needed")

        if not stopped:
            ctx.eligibility.set_job(job)
            preemption_enabled = (
                self.sched_config.preemption_enabled_for(job.type)
                if self.sched_config is not None else True)
            now = time.time()
            for tg in job.task_groups:
                scorer = NodeScorer(ctx, job, tg,
                                    preemption_enabled=preemption_enabled,
                                    current_priority=job.priority)
                for node in nodes:
                    existing = live.get((node.id, tg.name))
                    if existing is not None:
                        if existing.job_version == job.version:
                            continue  # in place and current
                        # destructive update
                        self.plan.append_stopped_alloc(
                            existing, "alloc is being updated due to job update")
                    # sysbatch: completed allocs shouldn't rerun
                    if self.sysbatch:
                        prior = next(
                            (a for a in all_allocs
                             if a.node_id == node.id and a.task_group == tg.name
                             and a.client_status == enums.ALLOC_CLIENT_COMPLETE
                             and a.job_version == job.version), None)
                        if prior is not None:
                            continue
                    metrics = ctx.new_metrics()
                    metrics.nodes_evaluated += 1
                    if not _class_feasible(ctx, job, tg, node):
                        self._record_failure(tg.name, ctx)
                        continue
                    option = scorer.rank(node)
                    if option is None:
                        self._record_failure(tg.name, ctx)
                        continue
                    alloc = Allocation(
                        id=generate_uuid(),
                        eval_id=ev.id,
                        name=alloc_name(job.id, tg.name, 0),
                        namespace=job.namespace,
                        node_id=node.id,
                        node_name=node.name,
                        job_id=job.id,
                        job=job,
                        job_version=job.version,
                        task_group=tg.name,
                        allocated_vec=tg.combined_resources().vec(),
                        allocated_ports=list(option.allocated_ports),
                        allocated_devices=dict(option.allocated_devices),
                        allocated_cores=list(option.allocated_cores),
                        desired_status=enums.ALLOC_DESIRED_RUN,
                        client_status=enums.ALLOC_CLIENT_PENDING,
                        metrics=metrics,
                        allocated_at=now,
                    )
                    if existing is not None:
                        alloc.previous_allocation = existing.id
                    if option.preempted_allocs:
                        for victim in option.preempted_allocs:
                            self.plan.append_preempted_alloc(victim, alloc.id)
                    self.plan.append_alloc(alloc)
                    self.queued_allocs[tg.name] = self.queued_allocs.get(tg.name, 0) + 1

        if self.plan.is_no_op() and not self.failed_tg_allocs:
            self._set_status(enums.EVAL_STATUS_COMPLETE, "")
            return True

        result, new_state = self.planner.submit_plan(self.plan)
        if new_state is not None:
            self.state = new_state
            full, _, _ = result.full_commit(self.plan)
            if not full:
                return False
        self._set_status(enums.EVAL_STATUS_COMPLETE, "")
        return True

    def _record_failure(self, tg_name: str, ctx: EvalContext) -> None:
        # system jobs don't create blocked evals; they surface failed
        # placements on the eval (reference scheduler_system.go)
        prev = self.failed_tg_allocs.get(tg_name)
        if prev is None:
            self.failed_tg_allocs[tg_name] = ctx.metrics
        else:
            prev.coalesced_failures += 1

    @property
    def annotations(self):
        """Per-TG desired-update counts for the dry-run plan endpoint
        (system jobs: one placement per eligible node)."""
        return {tg: {"place": n} for tg, n in self.queued_allocs.items()}

    def _set_status(self, status: str, desc: str) -> None:
        ev = _copy.copy(self.eval)
        ev.status = status
        ev.status_description = desc
        ev.failed_tg_allocs = self.failed_tg_allocs
        ev.queued_allocations = dict(self.queued_allocs)
        self.planner.update_eval(ev)
