"""nomadtrace: lightweight eval-lifecycle tracing.

A process-global `Tracer` records named spans into per-thread bounded
ring buffers. The hot path is lock-free: each ring has exactly one
writer (its owning thread), so appends are plain GIL-atomic list ops;
the registry of rings takes a lock only at ring creation and at
export-time snapshot. Every span exit also feeds the span's duration
into the metrics Registry under ``nomad.eval.phase.<name>`` so the
prometheus surface gains per-phase histograms for free.

Span records are plain tuples (see the ``R_*`` index constants):

    (name, trace, parent, span_id, t0, t1, thread, args)

``trace`` ties a span to one evaluation's lifecycle (``Evaluation.trace()``
— the eval id unless explicitly stamped). Batch-level spans that cover
several evals at once (a shared worker snapshot, a pipelined commit
round, a joint solver launch) carry ``traces=[...]`` inside ``args``
instead; raft-internal spans (fsync, replicate, apply) are trace-less
and attach to evals only by time overlap (obs/export.py gap
attribution).

Kill switch: ``NOMAD_TPU_TRACE=0`` disables the tracer at import; every
``span()`` call then returns a shared no-op singleton and ``event`` /
``add_span`` return before touching a clock — the instrumentation
compiles down to a bool check per call site.

Clock: ``time.time()`` (wall). It is shared with the broker's
``_enqueue_times`` side table (which powers the retroactive
``eval.queued`` span) and comparable across threads; span durations are
milliseconds-scale, far above its resolution.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from typing import List, Optional

# The Registry binds lazily: importing core.metrics here would run
# core/__init__ -> server -> broker -> back into this half-initialized
# package (obs must stay a leaf import for every subsystem).
_REGISTRY = None


def _registry():
    global _REGISTRY
    if _REGISTRY is None:
        from ..core.metrics import REGISTRY

        _REGISTRY = REGISTRY
    return _REGISTRY


# record tuple layout
R_NAME, R_TRACE, R_PARENT, R_ID, R_T0, R_T1, R_THREAD, R_ARGS = range(8)

# default per-thread ring capacity (records); a span record is a small
# tuple, so even 64 threads hold only a few MB at this bound
RING_CAP = int(os.environ.get("NOMAD_TPU_TRACE_RING", "8192"))

_ids = itertools.count(1)  # next() is GIL-atomic: one span-id sequence


class _NullSpan:
    """The disabled-tracer span: a stateless, re-enterable no-op.
    Doubles as the disabled bind() context."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **kv) -> None:
        return None


NULL_SPAN = _NullSpan()


class _Ring:
    """Bounded record ring with a single writer (its owning thread)."""

    __slots__ = ("buf", "cap", "idx")

    def __init__(self, cap: int):
        self.buf: list = []
        self.cap = cap
        self.idx = 0  # next overwrite position once full

    def append(self, rec: tuple) -> None:
        if len(self.buf) < self.cap:
            self.buf.append(rec)
        else:
            self.buf[self.idx] = rec
            self.idx = (self.idx + 1) % self.cap

    def snapshot(self) -> list:
        # cross-thread read of a single-writer ring: list() is one
        # GIL-atomic copy; a concurrent wrap can at worst misorder the
        # boundary records, and export sorts by t0 anyway
        buf = list(self.buf)
        if len(buf) < self.cap:
            return buf
        i = self.idx
        return buf[i:] + buf[:i]


class _Span:
    """One open span (context manager). Created only when the tracer is
    enabled; records itself into the calling thread's ring on exit."""

    __slots__ = ("_tr", "name", "trace", "args", "_parent", "sid", "t0")

    def __init__(self, tr: "Tracer", name: str, trace, args: dict):
        self._tr = tr
        self.name = name
        self.trace = trace
        self.args = args
        self._parent = 0
        self.sid = 0
        self.t0 = 0.0

    def __enter__(self):
        tl = self._tr._tl()
        stack = tl.stack
        if self.trace is None:
            if stack and stack[-1][1] is not None:
                self.trace = stack[-1][1]
            elif tl.bound:
                self.trace = tl.bound[-1]
        self._parent = stack[-1][0] if stack else 0
        self.sid = next(_ids)
        stack.append((self.sid, self.trace))
        self.t0 = time.time()
        return self

    def __exit__(self, *exc):
        t1 = time.time()
        tl = self._tr._tl()
        if tl.stack and tl.stack[-1][0] == self.sid:
            tl.stack.pop()
        tl.ring.append((self.name, self.trace, self._parent, self.sid,
                        self.t0, t1, tl.tid, self.args))
        _registry().observe("nomad.eval.phase." + self.name, t1 - self.t0)
        return False

    def set(self, **kv) -> None:
        """Attach args discovered mid-span (result sizes, verdicts)."""
        self.args.update(kv)


class _Bind:
    """Thread-local trace binding: spans opened inside inherit the
    bound trace id when they don't name one themselves."""

    __slots__ = ("_tr", "trace")

    def __init__(self, tr: "Tracer", trace):
        self._tr = tr
        self.trace = trace

    def __enter__(self):
        self._tr._tl().bound.append(self.trace)
        return self

    def __exit__(self, *exc):
        bound = self._tr._tl().bound
        if bound:
            bound.pop()
        return False


class Tracer:
    def __init__(self, enabled: Optional[bool] = None,
                 ring_cap: int = RING_CAP):
        if enabled is None:
            enabled = os.environ.get("NOMAD_TPU_TRACE", "1") != "0"
        self.enabled = bool(enabled)
        self.ring_cap = ring_cap
        self._local = threading.local()
        # ring registry: written once per thread generation under the
        # lock, read (snapshot) under the lock; ring CONTENTS stay
        # lock-free. _epoch bumps on clear(): a thread whose local ring
        # predates the current epoch lazily replaces it, so cleared
        # records never resurface
        self._reg_lock = threading.Lock()
        self._rings: dict = {}  # id(ring) -> _Ring
        self._epoch = 0

    def set_enabled(self, enabled: bool) -> None:
        self.enabled = bool(enabled)

    # -- thread-local state --

    def _tl(self):
        tl = self._local
        if getattr(tl, "ring", None) is None or tl.epoch != self._epoch:
            tl.ring = _Ring(self.ring_cap)
            tl.stack = getattr(tl, "stack", None) or []
            tl.bound = getattr(tl, "bound", None) or []
            tl.tid = threading.current_thread().name
            tl.epoch = self._epoch
            with self._reg_lock:
                self._rings[id(tl.ring)] = tl.ring
        return tl

    # -- recording --

    def span(self, name: str, trace=None, **args):
        """Open a named span as a context manager. ``trace`` defaults to
        the enclosing span's / bind()'s trace id."""
        if not self.enabled:
            return NULL_SPAN
        return _Span(self, name, trace, args)

    def bind(self, trace):
        """Context manager: spans opened inside (on this thread) inherit
        ``trace`` unless they name their own."""
        if not self.enabled:
            return NULL_SPAN
        return _Bind(self, trace)

    def add_span(self, name: str, t0: float, t1: float, trace=None,
                 **args) -> None:
        """Record a span retroactively from externally captured
        timestamps (e.g. the broker's enqueue-time side table)."""
        if not self.enabled:
            return
        tl = self._tl()
        tl.ring.append((name, trace, 0, next(_ids), t0, t1, tl.tid, args))
        _registry().observe("nomad.eval.phase." + name, max(0.0, t1 - t0))

    def event(self, name: str, trace=None, **args) -> None:
        """Record an instant (zero-duration span)."""
        if not self.enabled:
            return
        tl = self._tl()
        now = time.time()
        tl.ring.append((name, trace, 0, next(_ids), now, now, tl.tid, args))

    # -- export --

    def spans(self) -> List[tuple]:
        """Snapshot every thread's ring, merged and sorted by start
        time. Cheap enough for a scrape endpoint; never blocks
        writers."""
        with self._reg_lock:
            rings = list(self._rings.values())
        out: List[tuple] = []
        for r in rings:
            out.extend(r.snapshot())
        out.sort(key=lambda rec: rec[R_T0])
        return out

    def clear(self) -> None:
        """Drop all recorded spans (bench/test isolation): unregister
        every ring and bump the epoch so each thread re-registers a
        fresh one on its next record."""
        with self._reg_lock:
            self._rings.clear()
            self._epoch += 1


TRACER = Tracer()
