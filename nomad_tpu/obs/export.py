"""Trace export: Chrome ``trace_event`` JSON, per-phase percentile
breakdowns, and per-eval span-chain analysis with gap attribution.

The Chrome format is the one ``chrome://tracing`` / Perfetto load
directly: complete events (``"ph": "X"``) with microsecond timestamps,
one row per recording thread. ``python -m nomad_tpu.obs --export``
writes it; ``/v1/traces`` serves the same events inline.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .trace import R_ARGS, R_ID, R_NAME, R_PARENT, R_T0, R_T1, R_THREAD, \
    R_TRACE

# the canonical eval lifecycle, in order (OBSERVABILITY.md span
# taxonomy). A committed eval's trace must contain at least these;
# raft.* spans are trace-less and attach by time overlap.
EVAL_CHAIN = ("eval.queued", "worker.schedule", "plan.submit",
              "plan.verify", "plan.commit")


def chrome_trace(spans: List[tuple]) -> dict:
    """Render span records as a Chrome trace_event JSON object.
    Timestamps are µs relative to the earliest span so the viewer
    opens at t=0."""
    if not spans:
        return {"traceEvents": []}
    base = min(rec[R_T0] for rec in spans)
    events = []
    for rec in spans:
        args = {k: v for k, v in rec[R_ARGS].items()}
        if rec[R_TRACE] is not None:
            args["trace"] = rec[R_TRACE]
        ev = {
            "name": rec[R_NAME],
            "ph": "X",
            "ts": (rec[R_T0] - base) * 1e6,
            "dur": max(0.0, (rec[R_T1] - rec[R_T0]) * 1e6),
            "pid": 1,
            "tid": rec[R_THREAD],
            "args": args,
        }
        if rec[R_PARENT]:
            ev["args"]["parent_span"] = rec[R_PARENT]
        ev["args"]["span"] = rec[R_ID]
        events.append(ev)
    return {"traceEvents": events,
            "displayTimeUnit": "ms"}


def phase_breakdown(spans: List[tuple]) -> Dict[str, dict]:
    """Per-phase duration stats over a span snapshot: count, total,
    p50/p99/max in milliseconds. This is the offline twin of the
    ``nomad.eval.phase.*`` Registry histograms — computed from the
    exported spans so a saved trace file carries its own breakdown."""
    by_name: Dict[str, List[float]] = {}
    for rec in spans:
        d = rec[R_T1] - rec[R_T0]
        if d <= 0:
            continue  # instants
        by_name.setdefault(rec[R_NAME], []).append(d)
    out: Dict[str, dict] = {}
    for name, durs in sorted(by_name.items()):
        durs.sort()
        n = len(durs)
        out[name] = {
            "count": n,
            "total_ms": 1000.0 * sum(durs),
            "p50_ms": 1000.0 * durs[int(0.50 * (n - 1))],
            "p99_ms": 1000.0 * durs[int(round(0.99 * (n - 1)))],
            "max_ms": 1000.0 * durs[-1],
        }
    return out


def spans_for_trace(spans: List[tuple], trace_id: str) -> List[tuple]:
    """Every span covering one eval: spans stamped with its trace id
    plus batch-level spans whose ``traces`` arg lists it."""
    out = []
    for rec in spans:
        if rec[R_TRACE] == trace_id:
            out.append(rec)
        elif trace_id in (rec[R_ARGS].get("traces") or ()):
            out.append(rec)
    out.sort(key=lambda rec: (rec[R_T0], rec[R_T1]))
    return out


def chain_report(spans: List[tuple], trace_id: str,
                 required: tuple = EVAL_CHAIN) -> dict:
    """Analyze one eval's span chain: which lifecycle phases are
    present, whether the chain is contiguous, and — for every hole
    between consecutive top-level spans — which OTHER spans (typically
    trace-less raft work) overlap the hole, attributing the gap.

    Returns {complete, missing, spans: n, coverage, gaps: [...]} where
    each gap is {after, before, ms, attributed: [names]} and
    ``coverage`` is traced-time / wall-time over the eval's window."""
    mine = spans_for_trace(spans, trace_id)
    names = {rec[R_NAME] for rec in mine}
    missing = [n for n in required if n not in names]
    report = {"trace": trace_id, "spans": len(mine),
              "complete": not missing, "missing": missing,
              "gaps": [], "coverage": 0.0}
    if not mine:
        return report
    # top-level chain: the eval's own spans, skipping nested ones
    # (a child starts before its enclosing span ends)
    timeline = [rec for rec in mine if rec[R_T1] > rec[R_T0]]
    if not timeline:
        return report
    t_begin = min(rec[R_T0] for rec in timeline)
    t_end = max(rec[R_T1] for rec in timeline)
    covered = 0.0
    cursor = t_begin
    prev = None
    for rec in timeline:
        if rec[R_T0] > cursor:
            gap0, gap1 = cursor, rec[R_T0]
            attributed = sorted({
                other[R_NAME] for other in spans
                if other[R_T1] > other[R_T0]
                and other[R_T0] < gap1 and other[R_T1] > gap0
                and other is not rec and other not in mine})
            report["gaps"].append({
                "after": prev[R_NAME] if prev else None,
                "before": rec[R_NAME],
                "ms": 1000.0 * (gap1 - gap0),
                "attributed": attributed,
            })
            cursor = rec[R_T0]
        if rec[R_T1] > cursor:
            covered += rec[R_T1] - cursor
            cursor = rec[R_T1]
            prev = rec
    wall = t_end - t_begin
    report["coverage"] = covered / wall if wall > 0 else 1.0
    return report


def render_chain(report: dict) -> str:
    """One-paragraph human rendering of a chain_report (smoke output,
    OBSERVABILITY.md examples)."""
    lines = [f"trace {report['trace']}: {report['spans']} span(s), "
             f"coverage {report['coverage']:.0%}, "
             f"{'complete' if report['complete'] else 'MISSING ' + ','.join(report['missing'])}"]
    for g in report["gaps"]:
        who = ", ".join(g["attributed"]) or "untraced"
        lines.append(f"  gap {g['ms']:8.3f}ms {g['after']} -> "
                     f"{g['before']}: {who}")
    return "\n".join(lines)


def write_chrome_trace(path: str, spans: List[tuple],
                       breakdown: Optional[dict] = None) -> None:
    import json

    doc = chrome_trace(spans)
    doc["otherData"] = {"phases": breakdown or phase_breakdown(spans)}
    with open(path, "w") as f:
        json.dump(doc, f)
