"""Flight recorder: bounded per-subsystem event rings.

Where the tracer answers "where did this eval spend its time", the
flight recorder answers "what was the control plane DOING around the
failure": broker transitions, plan accept/reject verdicts with reasons,
raft term/role changes, solver launch stats. Each subsystem gets its
own ``deque(maxlen=...)`` ring — appends are GIL-atomic, so the record
path takes no locks — and ``chaos.InvariantChecker`` / the modelcheck
scenarios dump the merged timeline automatically on any invariant
failure, turning "invariant X failed at seed S" into a causal event
log.

Shares the tracer's ``NOMAD_TPU_TRACE=0`` kill switch: a disabled
recorder's ``record()`` is a bool check and a return.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

# events kept per subsystem; a dump prints the merged tail, so the ring
# only needs to cover the window between cause and detection
RING_EVENTS = int(os.environ.get("NOMAD_TPU_RECORDER_RING", "512"))


class FlightRecorder:
    def __init__(self, enabled: Optional[bool] = None,
                 ring_events: int = RING_EVENTS):
        if enabled is None:
            enabled = os.environ.get("NOMAD_TPU_TRACE", "1") != "0"
        self.enabled = bool(enabled)
        self.ring_events = ring_events
        # subsystem -> deque of (t, thread, event, fields); dict writes
        # race only on first touch of a new subsystem, guarded below
        self._rings: Dict[str, deque] = {}
        self._create_lock = threading.Lock()

    def set_enabled(self, enabled: bool) -> None:
        self.enabled = bool(enabled)

    def record(self, subsystem: str, event: str, **fields) -> None:
        if not self.enabled:
            return
        ring = self._rings.get(subsystem)
        if ring is None:
            with self._create_lock:
                ring = self._rings.setdefault(
                    subsystem, deque(maxlen=self.ring_events))
        ring.append((time.time(), threading.current_thread().name,
                     event, fields))

    def events(self, subsystem: Optional[str] = None) -> List[tuple]:
        """Merged (t, subsystem, thread, event, fields) records, oldest
        first. deque snapshots are GIL-atomic; no writer is blocked."""
        with self._create_lock:
            items = [(name, list(ring))
                     for name, ring in self._rings.items()
                     if subsystem is None or name == subsystem]
        out = [(t, name, thread, event, fields)
               for name, recs in items
               for (t, thread, event, fields) in recs]
        out.sort(key=lambda r: r[0])
        return out

    def dump_text(self, last: int = 80) -> str:
        """The causal timeline a human reads after an invariant failure:
        the merged tail, one line per event, relative timestamps."""
        evs = self.events()[-last:]
        if not evs:
            return ""
        t0 = evs[0][0]
        lines = []
        for t, subsystem, thread, event, fields in evs:
            kv = " ".join(f"{k}={v}" for k, v in fields.items())
            lines.append(f"+{t - t0:9.4f}s [{subsystem:<7}] {event:<18} "
                         f"{kv}  ({thread})")
        return "\n".join(lines)

    def clear(self) -> None:
        with self._create_lock:
            self._rings.clear()


RECORDER = FlightRecorder()
