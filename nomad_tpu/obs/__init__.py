"""nomadtrace: eval-lifecycle tracing + flight recorder
(OBSERVABILITY.md).

The two process-global singletons every subsystem imports:

- ``TRACER``  — span recording into per-thread bounded rings
  (obs/trace.py); export via ``python -m nomad_tpu.obs --export``,
  ``/v1/traces``, and the ``nomad.eval.phase.*`` Registry histograms.
- ``RECORDER`` — per-subsystem bounded event rings (obs/recorder.py);
  dumped automatically by chaos/modelcheck on invariant failures.

Both honor the ``NOMAD_TPU_TRACE=0`` kill switch (checked at import,
flippable at runtime via ``set_enabled``).
"""

from .recorder import RECORDER, FlightRecorder
from .trace import NULL_SPAN, TRACER, Tracer

__all__ = ["TRACER", "Tracer", "RECORDER", "FlightRecorder", "NULL_SPAN"]
