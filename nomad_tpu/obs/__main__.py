"""Trace tooling CLI.

    python -m nomad_tpu.obs --export out.json [--addr URL]
    python -m nomad_tpu.obs --trace-smoke

``--export`` writes a Chrome ``trace_event`` JSON file (load it in
chrome://tracing or https://ui.perfetto.dev). With ``--addr`` it scrapes
a running agent's ``/v1/traces``; without, it boots a small in-process
demo cluster, runs a workload, and exports that trace.

``--trace-smoke`` is the scripts/check.sh gate: a live 3-node cluster
with tracing on, every committed eval must show a COMPLETE
enqueue→dequeue→schedule→plan-submit→verify→commit span chain (the
raft fsync/apply spans must exist for gap attribution), then the same
workload with ``NOMAD_TPU_TRACE`` semantics off must record ZERO spans
(the kill switch actually kills). Exit 0 ok / 2 fail."""

from __future__ import annotations

import argparse
import logging
import shutil
import sys
import tempfile
import time

from . import RECORDER, TRACER
from .export import (EVAL_CHAIN, chain_report, phase_breakdown,
                     render_chain, write_chrome_trace)
from .trace import R_NAME

log = logging.getLogger("nomad_tpu.obs")


def _run_workload(cluster, leader, jobs_n: int):
    """Register jobs_n single-alloc jobs, enqueue their evals, drain.
    Returns the list of enqueued evals (each its own trace root)."""
    from .. import mock

    jobs = []
    for _ in range(jobs_n):
        j = mock.job()
        j.task_groups[0].count = 1
        j.task_groups[0].tasks[0].resources.cpu = 100
        j.task_groups[0].tasks[0].resources.memory_mb = 64
        jobs.append(j)
        leader.store.upsert_job(j)
    evals = [mock.eval_for(j, create_time=time.time()) for j in jobs]
    leader.store.upsert_evals(evals)
    for ev in evals:
        leader.server.broker.enqueue(ev)

    deadline = time.time() + 120
    while True:
        if leader.server.wait_for_idle(timeout=10.0,
                                       include_delayed=False) \
                and leader.server.blocked.blocked_count() == 0:
            snap = leader.local_store.snapshot()
            placed = [a for a in snap.allocs()
                      if not a.terminal_status()
                      and not a.server_terminal()]
            if len(placed) >= jobs_n:
                return evals
        if time.time() > deadline:
            raise RuntimeError("workload did not drain")
        time.sleep(0.05)


def _demo_cluster(tmp: str, jobs_n: int = 60, nodes_n: int = 20,
                  workers: int = 2):
    """A small live 3-node cluster + drained workload; yields
    (cluster, leader, evals). Caller stops the cluster."""
    from .. import mock
    from ..core.server import ServerConfig
    from ..raft.cluster import RaftCluster

    def config_fn(_i: int) -> ServerConfig:
        return ServerConfig(
            num_workers=workers, plan_commit_batching=True,
            eval_batch_size=4,
            heartbeat_ttl=3600.0, gc_interval=3600.0, nack_timeout=900.0,
            failed_eval_followup_delay=3600.0,
            failed_eval_unblock_interval=0.5)

    cluster = RaftCluster(3, config_fn=config_fn, data_dir=tmp)
    cluster.start()
    leader = cluster.wait_for_leader(timeout=15.0)
    if leader is None:
        cluster.stop()
        raise RuntimeError("no leader elected")
    for _ in range(nodes_n):
        leader.register_node(mock.node())
    evals = _run_workload(cluster, leader, jobs_n)
    return cluster, leader, evals


def export_trace(path: str, addr: str = "") -> int:
    if addr:
        import json
        import urllib.request

        with urllib.request.urlopen(
                addr.rstrip("/") + "/v1/traces?limit=0", timeout=10) as r:
            body = json.loads(r.read().decode())
        doc = body.get("trace", {"traceEvents": []})
        doc["otherData"] = {"phases": body.get("phases", {})}
        with open(path, "w") as f:
            json.dump(doc, f)
        print(f"wrote {len(doc['traceEvents'])} span(s) from {addr} "
              f"-> {path}")
        return 0
    # demo mode: boot a cluster, run a workload, export its spans
    TRACER.set_enabled(True)
    TRACER.clear()
    tmp = tempfile.mkdtemp(prefix="nomad-obs-export-")
    try:
        cluster, _leader, _evals = _demo_cluster(tmp)
        try:
            spans = TRACER.spans()
        finally:
            cluster.stop()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    write_chrome_trace(path, spans)
    print(f"wrote {len(spans)} span(s) from an in-process demo cluster "
          f"-> {path}")
    for name, row in phase_breakdown(spans).items():
        print(f"  {name:<22} n={row['count']:<5} p50={row['p50_ms']:8.3f}ms"
              f" p99={row['p99_ms']:8.3f}ms")
    return 0


def trace_smoke(jobs_n: int = 60) -> int:
    t0 = time.monotonic()
    TRACER.set_enabled(True)
    RECORDER.set_enabled(True)
    TRACER.clear()
    RECORDER.clear()
    tmp = tempfile.mkdtemp(prefix="nomad-obs-smoke-")
    try:
        cluster, leader, evals = _demo_cluster(tmp, jobs_n=jobs_n)
        try:
            spans = TRACER.spans()

            # 1) every committed eval's chain is complete
            incomplete = []
            for ev in evals:
                rep = chain_report(spans, ev.trace(), required=EVAL_CHAIN)
                if not rep["complete"]:
                    incomplete.append(rep)
            if incomplete:
                print("TRACE SMOKE: FAIL — incomplete span chain for "
                      f"{len(incomplete)}/{len(evals)} eval(s):")
                for rep in incomplete[:3]:
                    print(render_chain(rep))
                return 2

            # 2) the raft write path showed up (gap attribution fodder)
            names = {rec[R_NAME] for rec in spans}
            for must in ("raft.fsync", "raft.apply", "worker.snapshot",
                         "eval.persist"):
                if must not in names:
                    print(f"TRACE SMOKE: FAIL — no {must} span recorded")
                    return 2

            # 3) the recorder saw the control plane move
            if not RECORDER.events("broker") \
                    or not RECORDER.events("plan") \
                    or not RECORDER.events("raft"):
                print("TRACE SMOKE: FAIL — flight recorder missed a "
                      "subsystem (broker/plan/raft)")
                return 2

            # one sample chain for the human reading the CI log
            print(render_chain(chain_report(spans, evals[0].trace(),
                                            required=EVAL_CHAIN)))

            # 4) kill switch: same workload, tracing off, ZERO spans
            TRACER.set_enabled(False)
            RECORDER.set_enabled(False)
            TRACER.clear()
            RECORDER.clear()
            _run_workload(cluster, cluster.leader() or leader, 20)
            leftover = TRACER.spans()
            if leftover:
                print(f"TRACE SMOKE: FAIL — kill switch leaked "
                      f"{len(leftover)} span(s)")
                return 2
            if RECORDER.events():
                print("TRACE SMOKE: FAIL — kill switch leaked recorder "
                      "events")
                return 2
        finally:
            cluster.stop()
            TRACER.set_enabled(True)
            RECORDER.set_enabled(True)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    dt = time.monotonic() - t0
    print(f"TRACE SMOKE: ok — {len(evals)} eval(s) with complete "
          f"enqueue→commit span chains ({len(spans)} spans), kill "
          f"switch verified span-free, {dt:.1f}s")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="python -m nomad_tpu.obs")
    parser.add_argument("--export", metavar="PATH",
                        help="write a Chrome trace_event JSON file")
    parser.add_argument("--addr", default="",
                        help="scrape a running agent (e.g. "
                             "http://127.0.0.1:4646) instead of the "
                             "in-process demo")
    parser.add_argument("--trace-smoke", action="store_true",
                        help="live-cluster span-chain + kill-switch gate")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO if args.verbose else logging.WARNING,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    if args.trace_smoke:
        return trace_smoke()
    if args.export:
        return export_trace(args.export, addr=args.addr)
    parser.print_help()
    return 0


if __name__ == "__main__":
    sys.exit(main())
