"""Raft log entries and storage (reference hashicorp/raft log +
boltdb log store; in-memory here, with the same term/index invariants).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import List, Optional, Tuple


@dataclass(slots=True)
class Entry:
    index: int
    term: int
    command: tuple  # (op, payload) — see fsm.py


class RaftLog:
    """1-indexed append-only log guarded by a lock."""

    def __init__(self):
        self._entries: List[Entry] = []
        self._lock = threading.Lock()

    def last(self) -> Tuple[int, int]:
        """-> (last_index, last_term)."""
        with self._lock:
            if not self._entries:
                return 0, 0
            e = self._entries[-1]
            return e.index, e.term

    def term_at(self, index: int) -> int:
        if index == 0:
            return 0
        with self._lock:
            if index > len(self._entries):
                return -1
            return self._entries[index - 1].term

    def get(self, index: int) -> Optional[Entry]:
        with self._lock:
            if 1 <= index <= len(self._entries):
                return self._entries[index - 1]
            return None

    def slice_from(self, index: int, limit: int = 64) -> List[Entry]:
        with self._lock:
            return list(self._entries[index - 1: index - 1 + limit])

    def append(self, term: int, command: tuple) -> Entry:
        with self._lock:
            e = Entry(index=len(self._entries) + 1, term=term, command=command)
            self._entries.append(e)
            return e

    def append_batch(self, term: int, commands: List[tuple],
                     prev: Optional[Tuple[int, int]] = None
                     ) -> Optional[List[Entry]]:
        """Append a whole batch in one lock hold (the group-commit
        primitive; DurableLog adds the single-fsync disk write on top).

        When ``prev`` is given the append is conditional on the tail
        still being exactly ``(last_index, last_term)``: the log writer
        snapshots the tail under the node lock, builds the batch outside
        it, and any interleaved append — a config entry, a new leader's
        noop, a follower truncation after step-down — fails the
        compare-and-swap instead of landing the batch on a diverged log.
        Returns None on a CAS mismatch."""
        with self._lock:
            if not self._entries:
                tail = (0, 0)
            else:
                e = self._entries[-1]
                tail = (e.index, e.term)
            if prev is not None and tail != tuple(prev):
                return None
            batch = [Entry(index=tail[0] + 1 + i, term=term, command=c)
                     for i, c in enumerate(commands)]
            self._entries.extend(batch)
            return batch

    def append_entries(self, prev_index: int, entries: List[Entry]) -> bool:
        """Follower-side: truncate conflicts after prev_index, then
        append (the AppendEntries receiver rules). Returns True when a
        conflicting suffix was truncated (membership must be
        recomputed — a dropped entry may have been a config change)."""
        truncated = False
        with self._lock:
            for e in entries:
                pos = e.index - 1
                if pos < len(self._entries):
                    if self._entries[pos].term != e.term:
                        del self._entries[pos:]
                        self._entries.append(e)
                        truncated = True
                    # else: already have it
                else:
                    self._entries.append(e)
        return truncated

    def length(self) -> int:
        with self._lock:
            return len(self._entries)
