"""Replicated log (reference: hashicorp/raft wired in nomad/server.go:1365,
FSM nomad/fsm.go:228).

A compact Raft implementation — leader election with randomized
timeouts, log replication with commit-index advancement, follower
catch-up, and term-based safety — over a pluggable transport (in-process
for tests, the same shape a TCP transport plugs into). Committed entries
feed an FSM that applies state-store mutations, so every server holds an
identical MVCC store and any server's scheduler workers can plan against
local snapshots (the reference's architecture, SURVEY.md §2.5).

- log.py       — entries + in-memory log with term/index invariants
- node.py      — the Raft state machine (follower/candidate/leader)
- transport.py — in-process message bus between nodes
- fsm.py       — command codec: store mutations as replicated entries
- cluster.py   — ReplicatedServer: core.Server on top of the raft log
"""

from .cluster import RaftCluster, ReplicatedServer
from .node import RaftNode

__all__ = ["RaftNode", "RaftCluster", "ReplicatedServer"]
