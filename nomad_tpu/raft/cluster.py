"""Replicated server composition (reference nomad/server.go multi-server
+ leader.go establishLeadership/revokeLeadership).

Each ReplicatedServer owns a local MVCC store replicated via its raft
node; the embedded core.Server's leader-only subsystems (broker, plan
applier, workers, watchers) run only while this node holds leadership —
exactly the reference's establish/revoke cycle. Requests landing on a
follower are forwarded to the leader (reference nomad/rpc.go forward).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional

from ..core import loadctl
from ..core.loadctl import RetryLater
from ..core.server import Server, ServerConfig
from ..state import StateStore
from ..utils.backoff import Backoff, Retryer
from .fsm import FSM, RaftStore
from .node import NotLeaderError, RaftNode
from .transport import InProcTransport, RemoteCallError, TransportError

log = logging.getLogger("nomad_tpu.raft")


def _is_loopback_bind(bind: str) -> bool:
    """True when a host:port bind string stays on the local machine
    (loopback or unspecified-but-local test binds are NOT included:
    0.0.0.0/:: listen on every interface)."""
    host = bind.rsplit(":", 1)[0].strip("[]").lower()
    return (host in ("localhost", "::1")
            or host.startswith("127."))

FORWARD = ("register_job", "deregister_job", "dispatch_job",
           "scale_job", "revert_job",
           "register_node", "register_nodes", "heartbeat", "heartbeat_batch",
           "update_node_status", "update_node_drain",
           "update_node_eligibility", "deregister_node",
           "update_allocs_from_client", "stop_alloc",
           "create_eval", "create_job_eval",
           "set_scheduler_config",
           "promote_deployment", "fail_deployment",
           "put_variable", "delete_variable",
           "register_volume", "deregister_volume",
           "upsert_node_pool", "delete_node_pool",
           "upsert_namespace", "delete_namespace", "force_gc",
           "upsert_service_registrations", "delete_service_registrations",
           "delete_services_by_alloc",
           "upsert_acl_policy", "create_acl_token", "acl_bootstrap",
           "upsert_acl_role", "delete_acl_role",
           "upsert_auth_method", "delete_auth_method",
           "upsert_binding_rule", "delete_binding_rule", "acl_login",
           "oidc_auth_url", "oidc_complete_auth",
           "create_one_time_token", "exchange_one_time_token",
           "sign_workload_identity",
           "upsert_region", "delete_region")


class ReplicatedServer:
    def __init__(self, node_id: str, peers: List[str], transport,
                 config: Optional[ServerConfig] = None,
                 peer_lookup: Optional[Callable[[str], "ReplicatedServer"]] = None,
                 data_dir: Optional[str] = None,
                 snapshot_threshold: int = 1024,
                 bootstrap: bool = True,
                 dead_server_cleanup_s: Optional[float] = None,
                 gossip_bind: Optional[str] = None,
                 gossip_seeds: Optional[List[str]] = None,
                 batch: bool = True):
        self.id = node_id
        self.crashed = False  # set by crash(); chaos invariants skip dead nodes
        self.local_store = StateStore()
        self.fsm = FSM(self.local_store)
        self.data_dir = data_dir
        raft_log = stable = snapshots = None
        fsm_snapshot = fsm_restore = None
        fsm_capture = fsm_serialize = None
        if data_dir is not None:
            # durable mode: boltdb-equivalent log + stable + snapshot
            # files under <data_dir>/raft (reference server.go:1365)
            import os

            from ..state.persist import (capture_store, dump_store,
                                         restore_store, serialize_capture)
            from .durable import DurableLog, SnapshotStore, StableStore

            raft_dir = os.path.join(data_dir, "raft")
            os.makedirs(raft_dir, exist_ok=True)
            stable = StableStore(raft_dir)
            snapshots = SnapshotStore(raft_dir)
            raft_log = DurableLog(raft_dir)
            fsm_snapshot = lambda: dump_store(self.local_store)  # noqa: E731
            fsm_restore = lambda data: restore_store(self.local_store, data)  # noqa: E731
            # stall-free path: capture pins an MVCC generation under the
            # node lock (O(1)); serialization runs on the snapshot worker
            fsm_capture = lambda: capture_store(self.local_store)  # noqa: E731
            fsm_serialize = lambda cap: serialize_capture(self.local_store, cap)  # noqa: E731
        self.raft = RaftNode(node_id, peers, transport, self.fsm.apply,
                             on_leadership=self._on_leadership,
                             log=raft_log, stable=stable,
                             snapshots=snapshots,
                             fsm_snapshot=fsm_snapshot,
                             fsm_restore=fsm_restore,
                             fsm_capture=fsm_capture,
                             fsm_serialize=fsm_serialize,
                             snapshot_threshold=snapshot_threshold,
                             peer_addrs=getattr(transport, "peer_addrs", None),
                             on_config_change=self._on_config_change,
                             bootstrap=bootstrap,
                             dead_server_cleanup_s=dead_server_cleanup_s,
                             # batch=False preserves the pre-group-commit
                             # write path (bench A/B baseline)
                             batch=batch)
        self.store = RaftStore(self.local_store, self.raft)
        self.server = Server(config, store=self.store)
        # nomadload: proposes consult the server's admission plane, and
        # the proposal queue is its primary commit-path watermark
        self.raft.admission = self.server.loadctl
        self.server.loadctl.register_queue(
            "proposals", lambda: len(self.raft._proposals),
            self.server.config.loadctl_proposal_soft,
            self.server.config.loadctl_proposal_hard,
            commit_path=True)
        self._peer_lookup = peer_lookup
        self.transport = transport
        self._lock = threading.Lock()
        # cross-process forwarding: a SocketTransport dispatches incoming
        # "call" frames here (reference nomad/rpc.go forwardLeader)
        if hasattr(transport, "register_call_handler"):
            transport.register_call_handler(self._handle_remote_call)
        # gossip membership (reference nomad/serf.go): when enabled the
        # leader auto-joins gossip-discovered servers into the raft
        # configuration and reaps gossip-dead ones — `server join`
        # becomes "point a new server at ANY gossip address"
        self.gossip = None
        self._gossip_seeds = list(gossip_seeds or [])
        self._gossip_stop = threading.Event()
        self._gossip_dead_since = {}
        self._gossip_auto_join_disabled = False
        # seed (re-)join backoff: a lone agent whose seeds weren't up yet
        # keeps introducing itself, ever more slowly (utils/backoff.py)
        self._seed_backoff = Backoff(base=0.5, factor=2.0, cap=10.0)
        self._next_seed_join = 0.0
        if gossip_bind is not None:
            from .gossip import GossipAgent

            cfg = config or ServerConfig()
            if not cfg.gossip_key and not _is_loopback_bind(gossip_bind):
                # unkeyed gossip on a routable interface: anyone on the
                # network can inject ALIVE members, and the leader would
                # auto-join them as raft voters — a cluster takeover.
                # Keep membership visibility but refuse to act on it
                # (reference serf requires encrypt for WAN exposure)
                self._gossip_auto_join_disabled = True
                log.warning(
                    "gossip on %s binds a non-loopback interface with no "
                    "gossip_key: auto-join of gossip-discovered servers "
                    "is DISABLED (set gossip_key to enable)", gossip_bind)
            self.gossip = GossipAgent(
                node_id, gossip_bind,
                key=(cfg.gossip_key.encode() if cfg.gossip_key else None),
                meta={"rpc": getattr(transport, "bind_addr", ""),
                      "region": cfg.region})

    def _on_config_change(self, servers: Dict[str, str]) -> None:
        """Membership changed (config entry applied): teach the socket
        transport any new peer addresses so replication can reach them."""
        transport = self.transport
        addrs = getattr(transport, "peer_addrs", None)
        if addrs is None:
            return
        for sid, addr in servers.items():
            if addr and addrs.get(sid) != addr:
                addrs[sid] = addr

    def _handle_remote_call(self, method: str, args: tuple, kwargs: dict):
        if method == "raft_add_server":
            return self._membership_change("add_server", *args)
        if method == "raft_remove_server":
            return self._membership_change("remove_server", *args)
        if method == "raft_read_index":
            # follower read support: a remote follower asks us (the
            # presumed leader) for a read index (reference nomad's
            # forwarded Status.Peers/blocking-query pattern)
            consistent, timeout = args
            return self.raft.read_index(timeout=timeout,
                                        lease=not consistent)
        if method not in FORWARD:
            raise ValueError(f"method {method!r} is not forwardable")
        if not self.is_leader():
            raise NotLeaderError(self.raft.leader_id)
        return getattr(self.server, method)(*args, **kwargs)

    def _membership_change(self, op: str, *args):
        """Run a membership change on the leader: locally when this node
        leads, else one forwarded hop (the joiner only knows the address
        it contacted; this member knows the leader — reference
        nomad/serf.go join forwarding)."""
        for _ in Retryer(deadline_s=10.0, base=0.05, cap=0.5, jitter=0.25):
            if self.raft.is_leader():
                getattr(self.raft, op)(*args)
                return {"ok": True}
            lid = self.raft.leader_id
            if lid and lid != self.id and hasattr(self.transport, "call"):
                try:
                    return self.transport.call(
                        lid, f"raft_{op}", args, {})
                except RemoteCallError as e:
                    # real outcomes (unknown id, leader-removal refusal)
                    # must surface, not retry until the deadline
                    cls = self._WIRE_ERRORS.get(e.error_type)
                    if cls is not None:
                        raise cls(str(e)) from e
                    if e.error_type != "NotLeaderError":
                        raise
                except TransportError:
                    pass
        raise NotLeaderError(self.raft.leader_id)

    def join(self, contact_addr: str, timeout: float = 15.0) -> None:
        """Joiner-side: ask any live member at contact_addr to add this
        server to the cluster (agent `server join` — reference
        nomad/server.go:1602 Join via serf, here an explicit RPC)."""
        transport = self.transport
        if not hasattr(transport, "call"):
            raise RuntimeError("join requires the socket transport")
        contact_id = f"_join:{contact_addr}"
        transport.peer_addrs[contact_id] = contact_addr
        last_err = None
        try:
            for _ in Retryer(deadline_s=timeout, base=0.2, cap=1.0):
                try:
                    transport.call(contact_id, "raft_add_server",
                                   (self.id, transport.bind_addr), {})
                    return
                except (RemoteCallError, TransportError) as e:
                    last_err = e
        finally:
            transport.peer_addrs.pop(contact_id, None)
        raise TimeoutError(f"join via {contact_addr} failed: {last_err}")

    # -- lifecycle --

    def start(self) -> None:
        self.raft.start()
        if self.gossip is not None:
            self.gossip.start()
            for seed in self._gossip_seeds:
                self.gossip.join(seed)
            t = threading.Thread(target=self._run_gossip_reconcile,
                                 daemon=True,
                                 name=f"gossip-reconcile-{self.id}")
            t.start()

    def stop(self) -> None:
        self._gossip_stop.set()
        if self.gossip is not None:
            self.gossip.stop()
        # same lock as the leadership flip threads: a concurrent
        # establish/revoke must not interleave with shutdown
        with self._lock:
            if self.server._running:
                self.server.stop()
        self.raft.stop()

    def crash(self) -> None:
        """Abrupt kill (chaos harness): the node stops answering and
        sending immediately — no graceful leader handoff, no flush
        beyond what each append already fsynced — so the durable state
        left on disk is exactly what a real process crash leaves.
        Restart by building a fresh ReplicatedServer over the same
        data_dir (RaftCluster.restart)."""
        self.crashed = True
        if hasattr(self.transport, "unregister"):
            self.transport.unregister(self.id)
        self._gossip_stop.set()
        if self.gossip is not None:
            self.gossip.stop()
        self.raft.stop()
        with self._lock:
            if self.server._running:
                self.server.stop()
        if hasattr(self.raft.log, "close"):
            self.raft.log.close()

    def set_gossip_http(self, http_addr: str) -> None:
        """Advertise this server's agent HTTP address in gossip meta
        (WAN members use it to keep the federation region registry
        fresh). Bumps our incarnation so the change disseminates."""
        if self.gossip is None:
            return
        with self.gossip._lock:
            me = self.gossip.members[self.id]
            me["meta"]["http"] = http_addr
            me["inc"] += 1

    # -- gossip-driven autopilot (reference nomad/serf.go serverJoin /
    #    serverFailed feeding autopilot member reconciliation) --

    GOSSIP_RECONCILE_INTERVAL = 1.0

    def _run_gossip_reconcile(self) -> None:
        while not self._gossip_stop.wait(self.GOSSIP_RECONCILE_INTERVAL):
            self._maybe_rejoin_seeds()
            if not self.raft.is_leader():
                continue
            try:
                self._gossip_reconcile_once()
            except Exception:
                # transient raft state changes; next tick retries
                log.debug("gossip reconcile tick failed on %s",
                          self.id, exc_info=True)

    def _maybe_rejoin_seeds(self) -> None:
        """A single UDP join datagram to a not-yet-listening seed is
        simply lost: while this agent knows nobody but itself, keep
        re-introducing it to the seeds on an escalating backoff."""
        if self.gossip is None or not self._gossip_seeds:
            return
        if len(self.gossip.alive_members()) > 1:
            self._seed_backoff.reset()
            self._next_seed_join = 0.0
            return
        now = time.time()
        if now < self._next_seed_join:
            return
        self._next_seed_join = now + self._seed_backoff.next_delay()
        for seed in self._gossip_seeds:
            self.gossip.join(seed)

    # a gossip-DEAD verdict must persist this long before the leader
    # removes the voter: one dropped UDP probe or a brief stall must not
    # churn raft membership (the reference's autopilot applies the same
    # kind of grace before dead-server cleanup)
    GOSSIP_DEAD_REAP_S = 15.0

    def _gossip_reconcile_once(self) -> None:
        from .gossip import ALIVE, DEAD

        cfg_region = self.server.config.region
        members = self.gossip.snapshot()
        current = dict(self.raft.servers)
        now = time.time()
        dead_since = self._gossip_dead_since
        for mid in list(dead_since):
            m = members.get(mid)
            if m is None or m["status"] != DEAD:
                dead_since.pop(mid, None)
        for mid, m in members.items():
            meta = m.get("meta") or {}
            region = meta.get("region", cfg_region)
            if region != cfg_region:
                # WAN members maintain the federation registry instead
                # of joining this region's raft quorum
                http = meta.get("http", "")
                if http:
                    try:
                        snap_region = self.server.store.snapshot().region(
                            region)
                        if m["status"] != DEAD and (
                                snap_region is None
                                or snap_region.address != http):
                            self.server.upsert_region(
                                {"name": region, "address": http})
                    except Exception:
                        log.debug("federation registry upsert for region "
                                  "%s failed", region, exc_info=True)
                continue
            rpc = meta.get("rpc", "")
            if m["status"] == DEAD:
                if mid not in current or mid == self.id:
                    continue
                since = dead_since.setdefault(mid, now)
                if now - since < self.GOSSIP_DEAD_REAP_S:
                    continue
                # never remove a voter if the remaining set would lack
                # a gossip-alive majority (availability over cleanup)
                remaining = [sid for sid in current if sid != mid]
                alive = sum(
                    1 for sid in remaining
                    if sid == self.id
                    or (members.get(sid) or {}).get("status") == ALIVE)
                if remaining and alive < len(remaining) // 2 + 1:
                    continue
                try:
                    self.raft.remove_server(mid)
                except Exception:
                    log.debug("autopilot removal of dead server %s failed",
                              mid, exc_info=True)
            elif mid not in current and rpc:
                if self._gossip_auto_join_disabled:
                    # unkeyed non-loopback gossip (see __init__): treat
                    # discovered members as advisory only
                    continue
                try:
                    self.raft.add_server(mid, rpc)
                except Exception:
                    log.debug("autopilot join of gossip member %s failed",
                              mid, exc_info=True)

    def _on_leadership(self, is_leader: bool) -> None:
        # runs on raft threads; establish/revoke the leader subsystems
        # (leader.go:357/1488)
        def flip():
            with self._lock:
                if is_leader and not self.server._running:
                    self.server.start()
                elif not is_leader and self.server._running:
                    self.server.stop()

        threading.Thread(target=flip, daemon=True,
                         name=f"leadership-{self.id}").start()

    def remove_peer(self, server_id: str):
        """Operator removal of a server (reference `operator raft
        remove-peer`, nomad/operator_endpoint.go RaftRemovePeer)."""
        return self._membership_change("remove_server", server_id)

    # -- forwarded endpoint surface --

    def is_leader(self) -> bool:
        return self.raft.is_leader() and self.server._running

    # -- read path (follower reads) --

    def known_leader(self) -> bool:
        """X-Nomad-KnownLeader: does this server currently know who the
        leader is? A crashed/stopped node's stale leader_id doesn't
        count — its belief is frozen, not current."""
        if self.crashed or self.raft._stop.is_set():
            return False
        return bool(self.raft.leader_id)

    def last_contact(self) -> float:
        """X-Nomad-LastContact: seconds since last leader contact (0.0
        on the leader, inf when no leader was ever heard)."""
        return self.raft.last_contact_age()

    def read_index(self, consistent: bool = False, timeout: float = 2.0
                   ) -> int:
        """Obtain a linearizable read index from the leader — locally
        when this node leads, else one hop to the leader (in-process via
        peer_lookup or over the socket transport). The caller then waits
        for its LOCAL store to reach the index and serves the read from
        any server (the Raft §6.4 follower-read protocol)."""
        if self.raft.is_leader():
            return self.raft.read_index(timeout=timeout,
                                        lease=not consistent)
        lid = self.raft.leader_id
        if not lid or lid == self.id:
            raise NotLeaderError(lid)
        if self._peer_lookup is not None:
            peer = self._peer_lookup(lid)
            if peer is None:
                raise NotLeaderError(lid)
            return peer.raft.read_index(timeout=timeout,
                                        lease=not consistent)
        if hasattr(self.transport, "call"):
            try:
                return self.transport.call(
                    lid, "raft_read_index", (consistent, timeout), {})
            except RemoteCallError as e:
                if e.error_type in ("NotLeaderError", "TimeoutError"):
                    raise NotLeaderError(lid) from e
                cls = self._WIRE_ERRORS.get(e.error_type)
                if cls is not None:
                    raise cls(str(e)) from e
                raise
            except TransportError as e:
                # reads are idempotent: a torn call is just "no index"
                raise NotLeaderError(lid) from e
        raise NotLeaderError(lid)

    def wait_applied(self, index: int, timeout: float = 5.0) -> None:
        """Wait until the LOCAL fsm reaches a read_index() result."""
        self.raft.wait_applied(index, timeout)

    # forwarded endpoints raise these; the HTTP layer maps them to status
    # codes, so they must survive the socket hop as their concrete types.
    # RetryLater is nomadload's structured admission rejection (429 +
    # Retry-After): it must arrive typed so the follower's _forward does
    # NOT retry it — server-side retries of a shed request are exactly
    # the amplification the admission plane exists to prevent.
    _WIRE_ERRORS = {"KeyError": KeyError, "ValueError": ValueError,
                    "PermissionError": PermissionError,
                    "TimeoutError": TimeoutError, "RuntimeError": RuntimeError,
                    "RetryLater": RetryLater}

    def _forward(self, name: str, args: tuple, kwargs: dict):
        """Run the endpoint on the leader: locally if this node leads,
        in-process via peer_lookup, or over the socket transport
        (reference nomad/rpc.go:445 forward)."""
        # nomadload deadline propagation: the forward hop inherits the
        # request deadline bound at ingress — already-expired work drops
        # here, and the retry window never outlives the client
        rem = loadctl.remaining()
        if rem is not None and loadctl.drop_if_expired("forward"):
            raise TimeoutError("request deadline passed before forward")
        fwd_deadline = 5.0 if rem is None else max(0.05, min(5.0, rem))
        # jittered backoff instead of a fixed 20 ms poll: during an
        # election every forwarder on every node spins this loop, and
        # synchronized polls pile onto the freshly elected leader
        for _ in Retryer(deadline_s=fwd_deadline, base=0.02, cap=0.25,
                         jitter=0.5):
            if self.is_leader():
                return getattr(self.server, name)(*args, **kwargs)
            lid = self.raft.leader_id
            if lid and lid != self.id:
                if self._peer_lookup is not None:
                    peer = self._peer_lookup(lid)
                    if peer is not None and peer.is_leader():
                        return getattr(peer.server, name)(*args, **kwargs)
                elif hasattr(self.transport, "call"):
                    try:
                        return self.transport.call(lid, name, args, kwargs)
                    except RemoteCallError as e:
                        if e.error_type == "NotLeaderError":
                            # stale leader hint: wait for the next election
                            continue
                        cls = self._WIRE_ERRORS.get(e.error_type)
                        if cls is not None:
                            raise cls(str(e)) from e
                        raise
                    except TransportError as e:
                        # "connection died after the frame left" is NOT
                        # retriable: the leader may have applied the
                        # mutation, and these endpoints are not idempotent
                        # (create_acl_token, register_job evals)
                        if getattr(e, "maybe_delivered", False):
                            raise
                        # connect failure: definitely not delivered; retry
        raise NotLeaderError(self.raft.leader_id)

    def __getattr__(self, name: str):
        if name in FORWARD:
            def call(*args, **kwargs):
                return self._forward(name, args, kwargs)

            return call
        raise AttributeError(name)


class RaftCluster:
    """N in-process replicated servers on one transport (the reference's
    in-process multi-server test topology, nomad/testing.go)."""

    def __init__(self, n: int = 3, config_fn: Optional[Callable[[int], ServerConfig]] = None,
                 data_dir: Optional[str] = None, snapshot_threshold: int = 1024,
                 batch: bool = True):
        self.transport = InProcTransport()
        ids = [f"server-{i}" for i in range(n)]
        self._ids = ids
        self._config_fn = config_fn
        self._data_dir = data_dir
        self._snapshot_threshold = snapshot_threshold
        self._batch = batch
        self.servers: Dict[str, ReplicatedServer] = {}
        for i, node_id in enumerate(ids):
            cfg = config_fn(i) if config_fn else ServerConfig(heartbeat_ttl=30.0)
            node_dir = None
            if data_dir is not None:
                import os
                node_dir = os.path.join(data_dir, node_id)
                os.makedirs(node_dir, exist_ok=True)
            self.servers[node_id] = ReplicatedServer(
                node_id, ids, self.transport, cfg,
                peer_lookup=self.servers.get, data_dir=node_dir,
                snapshot_threshold=snapshot_threshold, batch=batch)

    def start(self) -> "RaftCluster":
        for s in self.servers.values():
            s.start()
        return self

    def stop(self) -> None:
        for s in self.servers.values():
            s.stop()
        if hasattr(self.transport, "close"):
            self.transport.close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- chaos crash/restart (the harness's server-death injection) --

    def crash(self, node_id: str) -> ReplicatedServer:
        """Kill one server abruptly (see ReplicatedServer.crash). The
        dead instance stays in self.servers until restart() replaces
        it, like a dead process whose data_dir persists."""
        server = self.servers[node_id]
        server.crash()
        return server

    def restart(self, node_id: str) -> ReplicatedServer:
        """Start a fresh ReplicatedServer over the crashed one's
        data_dir — the durable-recovery path a real restart takes.
        Meaningful only for clusters built with data_dir (otherwise the
        replacement boots empty and rejoins via snapshot transfer)."""
        old = self.servers[node_id]
        i = self._ids.index(node_id)
        cfg = (self._config_fn(i) if self._config_fn
               else ServerConfig(heartbeat_ttl=30.0))
        replacement = ReplicatedServer(
            node_id, self._ids, self.transport, cfg,
            peer_lookup=self.servers.get, data_dir=old.data_dir,
            snapshot_threshold=self._snapshot_threshold, batch=self._batch)
        self.servers[node_id] = replacement
        replacement.start()
        return replacement

    def wait_for_leader(self, timeout: float = 10.0) -> Optional[ReplicatedServer]:
        deadline = time.time() + timeout
        while time.time() < deadline:
            for s in self.servers.values():
                if s.is_leader():
                    return s
            time.sleep(0.02)
        return None

    def leader(self) -> Optional[ReplicatedServer]:
        for s in self.servers.values():
            if s.is_leader():
                return s
        return None

    def followers(self) -> List[ReplicatedServer]:
        return [s for s in self.servers.values() if not s.raft.is_leader()]

    def any_server(self) -> ReplicatedServer:
        return next(iter(self.servers.values()))
