"""Replicated server composition (reference nomad/server.go multi-server
+ leader.go establishLeadership/revokeLeadership).

Each ReplicatedServer owns a local MVCC store replicated via its raft
node; the embedded core.Server's leader-only subsystems (broker, plan
applier, workers, watchers) run only while this node holds leadership —
exactly the reference's establish/revoke cycle. Requests landing on a
follower are forwarded to the leader (reference nomad/rpc.go forward).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ..core.server import Server, ServerConfig
from ..state import StateStore
from .fsm import FSM, RaftStore
from .node import NotLeaderError, RaftNode
from .transport import InProcTransport, RemoteCallError, TransportError

FORWARD = ("register_job", "deregister_job", "dispatch_job",
           "scale_job", "revert_job",
           "register_node", "heartbeat",
           "update_node_status", "update_node_drain",
           "update_node_eligibility", "deregister_node",
           "update_allocs_from_client", "stop_alloc",
           "create_eval", "create_job_eval",
           "set_scheduler_config",
           "promote_deployment", "fail_deployment",
           "put_variable", "delete_variable",
           "register_volume", "deregister_volume",
           "upsert_node_pool", "delete_node_pool",
           "upsert_namespace", "delete_namespace", "force_gc",
           "upsert_service_registrations", "delete_service_registrations",
           "delete_services_by_alloc",
           "upsert_acl_policy", "create_acl_token", "acl_bootstrap",
           "upsert_acl_role", "delete_acl_role",
           "upsert_auth_method", "delete_auth_method",
           "upsert_binding_rule", "delete_binding_rule", "acl_login",
           "upsert_region", "delete_region")


class ReplicatedServer:
    def __init__(self, node_id: str, peers: List[str], transport,
                 config: Optional[ServerConfig] = None,
                 peer_lookup: Optional[Callable[[str], "ReplicatedServer"]] = None,
                 data_dir: Optional[str] = None,
                 snapshot_threshold: int = 1024,
                 bootstrap: bool = True,
                 dead_server_cleanup_s: Optional[float] = None):
        self.id = node_id
        self.local_store = StateStore()
        self.fsm = FSM(self.local_store)
        self.data_dir = data_dir
        log = stable = snapshots = None
        fsm_snapshot = fsm_restore = None
        if data_dir is not None:
            # durable mode: boltdb-equivalent log + stable + snapshot
            # files under <data_dir>/raft (reference server.go:1365)
            import os

            from ..state.persist import dump_store, restore_store
            from .durable import DurableLog, SnapshotStore, StableStore

            raft_dir = os.path.join(data_dir, "raft")
            os.makedirs(raft_dir, exist_ok=True)
            stable = StableStore(raft_dir)
            snapshots = SnapshotStore(raft_dir)
            log = DurableLog(raft_dir)
            fsm_snapshot = lambda: dump_store(self.local_store)  # noqa: E731
            fsm_restore = lambda data: restore_store(self.local_store, data)  # noqa: E731
        self.raft = RaftNode(node_id, peers, transport, self.fsm.apply,
                             on_leadership=self._on_leadership,
                             log=log, stable=stable, snapshots=snapshots,
                             fsm_snapshot=fsm_snapshot,
                             fsm_restore=fsm_restore,
                             snapshot_threshold=snapshot_threshold,
                             peer_addrs=getattr(transport, "peer_addrs", None),
                             on_config_change=self._on_config_change,
                             bootstrap=bootstrap,
                             dead_server_cleanup_s=dead_server_cleanup_s)
        self.store = RaftStore(self.local_store, self.raft)
        self.server = Server(config, store=self.store)
        self._peer_lookup = peer_lookup
        self.transport = transport
        self._lock = threading.Lock()
        # cross-process forwarding: a SocketTransport dispatches incoming
        # "call" frames here (reference nomad/rpc.go forwardLeader)
        if hasattr(transport, "register_call_handler"):
            transport.register_call_handler(self._handle_remote_call)

    def _on_config_change(self, servers: Dict[str, str]) -> None:
        """Membership changed (config entry applied): teach the socket
        transport any new peer addresses so replication can reach them."""
        transport = self.transport
        addrs = getattr(transport, "peer_addrs", None)
        if addrs is None:
            return
        for sid, addr in servers.items():
            if addr and addrs.get(sid) != addr:
                addrs[sid] = addr

    def _handle_remote_call(self, method: str, args: tuple, kwargs: dict):
        if method == "raft_add_server":
            return self._membership_change("add_server", *args)
        if method == "raft_remove_server":
            return self._membership_change("remove_server", *args)
        if method not in FORWARD:
            raise ValueError(f"method {method!r} is not forwardable")
        if not self.is_leader():
            raise NotLeaderError(self.raft.leader_id)
        return getattr(self.server, method)(*args, **kwargs)

    def _membership_change(self, op: str, *args):
        """Run a membership change on the leader: locally when this node
        leads, else one forwarded hop (the joiner only knows the address
        it contacted; this member knows the leader — reference
        nomad/serf.go join forwarding)."""
        deadline = time.time() + 10.0
        while time.time() < deadline:
            if self.raft.is_leader():
                getattr(self.raft, op)(*args)
                return {"ok": True}
            lid = self.raft.leader_id
            if lid and lid != self.id and hasattr(self.transport, "call"):
                try:
                    return self.transport.call(
                        lid, f"raft_{op}", args, {})
                except RemoteCallError as e:
                    # real outcomes (unknown id, leader-removal refusal)
                    # must surface, not retry until the deadline
                    cls = self._WIRE_ERRORS.get(e.error_type)
                    if cls is not None:
                        raise cls(str(e)) from e
                    if e.error_type != "NotLeaderError":
                        raise
                except TransportError:
                    pass
            time.sleep(0.05)
        raise NotLeaderError(self.raft.leader_id)

    def join(self, contact_addr: str, timeout: float = 15.0) -> None:
        """Joiner-side: ask any live member at contact_addr to add this
        server to the cluster (agent `server join` — reference
        nomad/server.go:1602 Join via serf, here an explicit RPC)."""
        transport = self.transport
        if not hasattr(transport, "call"):
            raise RuntimeError("join requires the socket transport")
        contact_id = f"_join:{contact_addr}"
        transport.peer_addrs[contact_id] = contact_addr
        deadline = time.time() + timeout
        last_err = None
        try:
            while time.time() < deadline:
                try:
                    transport.call(contact_id, "raft_add_server",
                                   (self.id, transport.bind_addr), {})
                    return
                except (RemoteCallError, TransportError) as e:
                    last_err = e
                    time.sleep(0.2)
        finally:
            transport.peer_addrs.pop(contact_id, None)
        raise TimeoutError(f"join via {contact_addr} failed: {last_err}")

    # -- lifecycle --

    def start(self) -> None:
        self.raft.start()

    def stop(self) -> None:
        if self.server._running:
            self.server.stop()
        self.raft.stop()

    def _on_leadership(self, is_leader: bool) -> None:
        # runs on raft threads; establish/revoke the leader subsystems
        # (leader.go:357/1488)
        def flip():
            with self._lock:
                if is_leader and not self.server._running:
                    self.server.start()
                elif not is_leader and self.server._running:
                    self.server.stop()

        threading.Thread(target=flip, daemon=True,
                         name=f"leadership-{self.id}").start()

    def remove_peer(self, server_id: str):
        """Operator removal of a server (reference `operator raft
        remove-peer`, nomad/operator_endpoint.go RaftRemovePeer)."""
        return self._membership_change("remove_server", server_id)

    # -- forwarded endpoint surface --

    def is_leader(self) -> bool:
        return self.raft.is_leader() and self.server._running

    # forwarded endpoints raise these; the HTTP layer maps them to status
    # codes, so they must survive the socket hop as their concrete types
    _WIRE_ERRORS = {"KeyError": KeyError, "ValueError": ValueError,
                    "PermissionError": PermissionError,
                    "TimeoutError": TimeoutError, "RuntimeError": RuntimeError}

    def _forward(self, name: str, args: tuple, kwargs: dict):
        """Run the endpoint on the leader: locally if this node leads,
        in-process via peer_lookup, or over the socket transport
        (reference nomad/rpc.go:445 forward)."""
        deadline = time.time() + 5.0
        while time.time() < deadline:
            if self.is_leader():
                return getattr(self.server, name)(*args, **kwargs)
            lid = self.raft.leader_id
            if lid and lid != self.id:
                if self._peer_lookup is not None:
                    peer = self._peer_lookup(lid)
                    if peer is not None and peer.is_leader():
                        return getattr(peer.server, name)(*args, **kwargs)
                elif hasattr(self.transport, "call"):
                    try:
                        return self.transport.call(lid, name, args, kwargs)
                    except RemoteCallError as e:
                        if e.error_type == "NotLeaderError":
                            # stale leader hint: wait for the next election
                            time.sleep(0.02)
                            continue
                        cls = self._WIRE_ERRORS.get(e.error_type)
                        if cls is not None:
                            raise cls(str(e)) from e
                        raise
                    except TransportError as e:
                        # "connection died after the frame left" is NOT
                        # retriable: the leader may have applied the
                        # mutation, and these endpoints are not idempotent
                        # (create_acl_token, register_job evals)
                        if getattr(e, "maybe_delivered", False):
                            raise
                        # connect failure: definitely not delivered; retry
            time.sleep(0.02)
        raise NotLeaderError(self.raft.leader_id)

    def __getattr__(self, name: str):
        if name in FORWARD:
            def call(*args, **kwargs):
                return self._forward(name, args, kwargs)

            return call
        raise AttributeError(name)


class RaftCluster:
    """N in-process replicated servers on one transport (the reference's
    in-process multi-server test topology, nomad/testing.go)."""

    def __init__(self, n: int = 3, config_fn: Optional[Callable[[int], ServerConfig]] = None,
                 data_dir: Optional[str] = None, snapshot_threshold: int = 1024):
        self.transport = InProcTransport()
        ids = [f"server-{i}" for i in range(n)]
        self.servers: Dict[str, ReplicatedServer] = {}
        for i, node_id in enumerate(ids):
            cfg = config_fn(i) if config_fn else ServerConfig(heartbeat_ttl=30.0)
            node_dir = None
            if data_dir is not None:
                import os
                node_dir = os.path.join(data_dir, node_id)
                os.makedirs(node_dir, exist_ok=True)
            self.servers[node_id] = ReplicatedServer(
                node_id, ids, self.transport, cfg,
                peer_lookup=self.servers.get, data_dir=node_dir,
                snapshot_threshold=snapshot_threshold)

    def start(self) -> "RaftCluster":
        for s in self.servers.values():
            s.start()
        return self

    def stop(self) -> None:
        for s in self.servers.values():
            s.stop()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def wait_for_leader(self, timeout: float = 10.0) -> Optional[ReplicatedServer]:
        deadline = time.time() + timeout
        while time.time() < deadline:
            for s in self.servers.values():
                if s.is_leader():
                    return s
            time.sleep(0.02)
        return None

    def leader(self) -> Optional[ReplicatedServer]:
        for s in self.servers.values():
            if s.is_leader():
                return s
        return None

    def followers(self) -> List[ReplicatedServer]:
        return [s for s in self.servers.values() if not s.raft.is_leader()]

    def any_server(self) -> ReplicatedServer:
        return next(iter(self.servers.values()))
