"""FSM: replicated commands -> state-store mutations
(reference nomad/fsm.go:228 applying ~60 raft message types).

A command is ("op", args) where op names a StateStore mutation method.
Payloads are deep-copied before apply so replicas never share mutable
objects, and because every replica applies the identical command
sequence, store generation numbers (indexes) agree across the cluster.

RaftStore presents the StateStore surface: reads hit the local store,
mutations propose through the raft node and block until committed and
applied locally — the write path every core.Server subsystem already
uses, so replication slots in without touching them.
"""

from __future__ import annotations

import copy
import time
from typing import Any, List

MUTATIONS = {
    "upsert_node", "upsert_nodes", "update_node_status",
    "update_nodes_status", "update_node_eligibility",
    "update_node_drain", "delete_node",
    "upsert_job", "delete_job", "update_job_status",
    "upsert_evals", "delete_evals",
    "upsert_allocs", "update_allocs_from_client",
    "update_alloc_desired_transitions",
    "upsert_plan_results", "upsert_plan_results_batch",
    "upsert_deployment", "update_deployment_status", "delete_deployment",
    "upsert_acl_policy", "delete_acl_policy",
    "upsert_acl_token", "delete_acl_token",
    "upsert_acl_role", "delete_acl_role",
    "upsert_auth_method", "delete_auth_method",
    "upsert_binding_rule", "delete_binding_rule",
    "gc_expired_acl_tokens", "upsert_region", "delete_region",
    "set_scheduler_configuration",
    "upsert_one_time_token", "delete_one_time_token",
    "take_one_time_token", "gc_one_time_tokens",
    "append_scaling_event",
    "upsert_variable", "delete_variable",
    "upsert_volume", "delete_volume", "reap_volume_claims",
    "upsert_node_pool", "delete_node_pool",
    "upsert_namespace", "delete_namespace",
    "upsert_service_registrations", "delete_service_registrations",
    "delete_services_by_alloc",
    "gc_terminal_allocs", "compact", "restore_dump",
}


def _refuse_wallclock() -> float:
    raise RuntimeError(
        "wall-clock read during a replicated apply: a timestamped command "
        "reached the store without an explicit ts — the proposer must stamp "
        "it (RaftStore fills ts for every TIMESTAMPED op)")


class FSM:
    def __init__(self, store):
        self.store = store
        # A replica applying the shared log must never stamp local time:
        # replace the store's ts-fallback clock with a guard so any
        # mutator that would read wall clock fails loudly instead of
        # silently diverging from its peers.
        store._clock = _refuse_wallclock

    def apply(self, command: tuple) -> Any:
        op, args, kwargs = command
        if op == "noop":
            return None  # leader barrier entry (raft/node.py _become_leader_locked)
        if op not in MUTATIONS:
            raise ValueError(f"unknown FSM op {op!r}")
        if op in TIMESTAMPED and kwargs.get("ts") is None:
            # catch the divergence at the boundary, with the op name,
            # rather than via the _clock guard deep in a mutator
            raise ValueError(
                f"replicated {op!r} command carries no ts: replicas "
                "would each stamp their own apply time and diverge")
        fn = getattr(self.store, op)
        # each replica must own its objects
        args = copy.deepcopy(args)
        kwargs = copy.deepcopy(kwargs)
        return fn(*args, **kwargs)


# Mutations that stamp wall-clock times must receive the time from the
# proposer inside the replicated command: a follower replaying the log at
# catch-up time would otherwise stamp replay-time and diverge from the
# leader on time-gated decisions (gc_terminal_allocs cutoffs). The
# reference embeds times in the raft request structs for the same reason.
TIMESTAMPED = {
    "gc_expired_acl_tokens", "gc_one_time_tokens",
    "take_one_time_token",
    "upsert_evals", "upsert_allocs", "update_allocs_from_client",
    "upsert_plan_results", "upsert_plan_results_batch", "update_node_status",
    "update_nodes_status",
    "update_alloc_desired_transitions",
}


class RaftStore:
    """StateStore facade: local reads, replicated writes."""

    def __init__(self, store, raft_node):
        self._store = store
        self._raft = raft_node

    def __getattr__(self, name: str):
        if name in MUTATIONS:
            def propose(*args, **kwargs):
                if name in TIMESTAMPED and kwargs.get("ts") is None:
                    kwargs["ts"] = time.time()
                return self._raft.apply((name, args, kwargs))

            return propose
        return getattr(self._store, name)

    @property
    def can_propose_async(self) -> bool:
        """True when the raft node runs the group-commit log writer —
        the prerequisite for propose_async/wait_applied. Callers (the
        plan applier's commit pipeline) probe this to decide whether
        commit rounds may overlap."""
        return bool(getattr(self._raft, "batch", False))

    def propose_async(self, name: str, *args, **kwargs):
        """Start a replicated mutation without waiting for its commit:
        returns a proposal handle for wait_applied. Timestamp stamping
        matches the synchronous propose path (the ts must be fixed at
        propose time, not apply time — see TIMESTAMPED). Because
        proposal order at the raft node is log order, a single proposer
        pipelining rounds through this API keeps FSM apply order equal
        to its propose order."""
        if name not in MUTATIONS:
            raise AttributeError(f"{name} is not a replicated mutation")
        if name in TIMESTAMPED and kwargs.get("ts") is None:
            kwargs["ts"] = time.time()
        return self._raft.apply_async((name, args, kwargs))

    def wait_applied(self, prop, timeout: float = 30.0):
        """Block until a propose_async proposal is committed and
        applied locally; returns the FSM result (the raft index for
        store mutations)."""
        return self._raft.apply_wait(prop, timeout)

    # explicit read-path passthroughs used as attributes (not calls)
    @property
    def latest_index(self) -> int:
        return self._store.latest_index
