"""The Raft state machine (leader election + log replication).

Follows the Raft paper's receiver/sender rules: randomized election
timeouts, term-based vote safety with the up-to-date log check, leader
append-entries with per-peer next/match indexes, and commit advancement
restricted to current-term entries. Committed commands are applied to
the FSM in log order on a dedicated apply thread; leader-side apply()
blocks until the entry is both committed and locally applied, giving
the linearizable write the plan applier needs.

The write path is batched at every stage (hashicorp/raft's leader
loop + group commit, PERF.md "The replicated write path"):

- **Group commit** — apply() enqueues the proposal and a log-writer
  thread drains the whole queue, deep-copies the batch outside the node
  lock, and lands it with ONE buffered write + ONE fsync
  (DurableLog.append_batch). RPC handlers and the tick thread never
  block on client-write disk I/O.
- **Pipelined replication** — one replicator thread per peer, woken by
  a condition variable on every append and commit advance; the timed
  wait doubles as the idle-heartbeat fallback. Catch-up uses the
  follower's conflict hint (conflict_term/first_index) instead of
  decrement-by-one, and followers persist each entry batch with a
  single fsync before acking.
- **Batched apply** — the apply thread applies a whole committed range
  per lock hold with one notify_all; leader-side waiters are per-
  proposal events in a registry (no polling, no unbounded results map).

`batch=False` keeps the pre-batch single-proposal path (synchronous
append+fsync under the lock, tick-paced replication) for A/B
comparison — bench.py's raft_commit_throughput_3node rung.
"""

from __future__ import annotations

import copy
import json
import logging
import random
import threading
import time
from typing import Callable, Dict, List, Optional

from ..obs import NULL_SPAN, RECORDER, TRACER
from ..utils.backoff import Retryer
from .durable import MemorySnapshotSink, snapshot_digest
from .log import Entry, RaftLog

log = logging.getLogger("nomad_tpu.raft")

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"

# per-AppendEntries in-flight window (entries per RPC); the replicator
# streams back-to-back windows while a peer has backlog
MAX_APPEND_ENTRIES = 256
# cap on proposals landed per log-writer flush: bounds the size of one
# buffered write (and the blast radius of one fsync fault)
MAX_GROUP_COMMIT = 1024
# committed entries applied per lock hold: large enough to amortize the
# lock, small enough that RPC handlers never stall behind a big backlog
APPLY_CHUNK = 64
# install-snapshot transfer chunk (Raft §7 offset/done protocol): large
# enough to amortize per-frame overhead, small enough that one frame
# never trips the transport's frame cap and a torn transfer wastes
# little resend work
SNAPSHOT_CHUNK_BYTES = 1 << 20


class _Proposal:
    """A leader-side write waiting for commit + local apply. The event
    replaces the old 0.1 s polling wait; `command` doubles as an
    identity token so a result can never be delivered to a waiter whose
    registration lost the append CAS (see _commit_batch). `deadline`
    (absolute, time.time() base) is stamped from the nomadload
    request context at propose time: the log writer drops proposals
    whose waiter has already given up instead of burning an fsync slot
    on them (core/loadctl.py deadline propagation)."""

    __slots__ = ("command", "index", "result", "error", "done", "deadline")

    def __init__(self, command: tuple, deadline: Optional[float] = None):
        self.command = command
        self.index: Optional[int] = None
        self.result: object = None
        self.error: Optional[BaseException] = None
        self.done = threading.Event()
        self.deadline = deadline


_loadctl = None


def _lc():
    """Lazy nomadload accessor: core imports raft, so raft reaches the
    admission/deadline plane at call time only (the state/watch.py
    lazy-registry pattern)."""
    global _loadctl
    if _loadctl is None:
        from ..core import loadctl as _m
        _loadctl = _m
    return _loadctl


class RaftNode:
    def __init__(self, node_id: str, peers: List[str], transport,
                 fsm_apply: Callable[[tuple], object],
                 election_timeout: float = 0.3,
                 heartbeat_interval: float = 0.05,
                 on_leadership: Optional[Callable[[bool], None]] = None,
                 log=None, stable=None, snapshots=None,
                 fsm_snapshot: Optional[Callable[[], dict]] = None,
                 fsm_restore: Optional[Callable[[dict], None]] = None,
                 snapshot_threshold: int = 1024,
                 peer_addrs: Optional[Dict[str, str]] = None,
                 on_config_change: Optional[Callable[[Dict[str, str]], None]] = None,
                 bootstrap: bool = True,
                 dead_server_cleanup_s: Optional[float] = None,
                 batch: bool = True,
                 max_append_entries: int = MAX_APPEND_ENTRIES,
                 fsm_capture: Optional[Callable[[], object]] = None,
                 fsm_serialize: Optional[Callable[[object], dict]] = None,
                 snapshot_chunk_bytes: int = SNAPSHOT_CHUNK_BYTES,
                 lease_duration: Optional[float] = None):
        self.id = node_id
        # membership: server id -> address ("" when the transport
        # resolves ids directly). Config-change log entries rewrite this
        # at APPEND time (the standard single-server-change rule; see
        # change_config) — reference nomad/server.go AddVoter/
        # RemoveServer via hashicorp/raft.
        self.servers: Dict[str, str] = {node_id: (peer_addrs or {}).get(node_id, "")}
        for p in peers:
            if p != node_id:
                self.servers[p] = (peer_addrs or {}).get(p, "")
        self.peers = [p for p in self.servers if p != node_id]
        self.on_config_change = on_config_change
        # a non-bootstrap node with no peers (a joiner) must NOT elect
        # itself leader of a one-node cluster; it waits to learn the
        # real membership from the leader's append_entries
        self.bootstrap = bootstrap
        self.dead_server_cleanup_s = dead_server_cleanup_s
        self.batch = batch
        self.max_append_entries = max_append_entries
        self._last_contact: Dict[str, float] = {}
        self._config_index = 0  # log index of the latest config entry
        # replication state precedes the durability restore below:
        # a recovered snapshot/log config calls _set_servers_locked, which
        # maintains these
        self._next_index: Dict[str, int] = {}
        self._match_index: Dict[str, int] = {}
        # per-peer replicator scheduling: next idle-heartbeat time, the
        # leader commit index last acked down, and the retry-backoff
        # gate for unreachable peers
        self._next_heartbeat: Dict[str, float] = {}
        self._peer_commit: Dict[str, int] = {}
        self._repl_backoff: Dict[str, float] = {}
        self._replicators: Dict[str, threading.Thread] = {}
        self._started = False
        self.transport = transport
        self.fsm_apply = fsm_apply
        self.on_leadership = on_leadership
        self.election_timeout = election_timeout
        self.heartbeat_interval = heartbeat_interval

        self.state = FOLLOWER
        self.current_term = 0
        self.voted_for: Optional[str] = None
        self.log = log if log is not None else RaftLog()
        self.commit_index = 0
        self.last_applied = 0
        self.leader_id: Optional[str] = None
        # Leader lease for read_index: a read may skip the heartbeat
        # confirmation round while a quorum of peers acked within this
        # window. Safe at half the election timeout because followers
        # refuse votes while they heard from a live leader within a full
        # election_timeout (_on_request_vote leader-stickiness): by the
        # time a rival CAN win votes, any lease granted on pre-partition
        # acks has expired.
        self.lease_duration = (lease_duration if lease_duration is not None
                               else election_timeout * 0.5)
        # index of this term's barrier noop: reads wait for it to commit
        # (Raft §6.4 / §8 — earlier-term commits aren't known final
        # until a current-term entry commits on top)
        self._term_start_index = 0

        # durability (raft/durable.py); all optional — in-memory otherwise
        self.stable = stable
        self.snapshots = snapshots
        self.fsm_snapshot = fsm_snapshot
        self.fsm_restore = fsm_restore
        self.snapshot_threshold = snapshot_threshold
        # stall-free capture: fsm_capture pins an O(1) MVCC handle under
        # the node lock; fsm_serialize turns it into the snapshot dict on
        # a worker thread, outside the lock. When unset, _maybe_snapshot
        # falls back to the legacy under-lock fsm_snapshot path.
        self.fsm_capture = fsm_capture
        self.fsm_serialize = fsm_serialize
        self.snapshot_chunk_bytes = snapshot_chunk_bytes
        if stable is not None:
            self.current_term = stable.term
            self.voted_for = stable.voted_for
        if snapshots is not None and fsm_restore is not None:
            snap = snapshots.load()
            if snap is not None:
                fsm_restore(snap["data"])
                self.commit_index = snap["index"]
                self.last_applied = snap["index"]
                if snap.get("servers"):
                    self._set_servers_locked(dict(snap["servers"]))
        # the config to fall back to if a log truncation drops the only
        # config entry (snapshot membership, else the bootstrap peers)
        self._fallback_servers = dict(self.servers)
        # membership survives restarts: the latest config entry in the
        # recovered log wins over the snapshot's
        self._recover_config_from_log_locked()
        self._last_leader_contact = 0.0

        self._snap_inflight: set = set()  # peers mid-install-snapshot
        self._snap_active = False  # a local snapshot worker is running
        # follower-side chunk accumulator: {"leader","term","index","sink"}
        self._snap_rx: Optional[dict] = None
        # snapshot worker/sender threads, joined by stop(); pruned on
        # each spawn so the list stays bounded
        self._bg_threads: List[threading.Thread] = []
        self._lock = threading.RLock()
        self._apply_cond = threading.Condition(self._lock)
        # both conditions share the node lock (so notify is race-free
        # with the state they guard) but carry distinct wait-sets: the
        # log-writer sleeps on _propose_cond, replicators on _repl_cond
        self._propose_cond = threading.Condition(self._lock)
        self._repl_cond = threading.Condition(self._lock)
        self._deadline = self._new_deadline()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        # the group-commit queue and the waiter registry: proposals wait
        # here for the log-writer, then (keyed by index) for commit +
        # apply. Results without a registered waiter are dropped at
        # apply time — nothing accumulates.
        self._proposals: List[_Proposal] = []
        self._waiters: Dict[int, _Proposal] = {}
        self._autopilot: Optional[threading.Thread] = None
        # nomadload: the owning server's AdmissionController (set by
        # ReplicatedServer.attach); None = no admission at propose
        self.admission = None

        transport.register(node_id, self.handle)

    # -- lifecycle --

    def start(self) -> None:
        for name, fn in (("tick", self._run_tick),
                         ("apply", self._run_apply),
                         ("logwriter", self._run_log_writer)):
            t = threading.Thread(target=fn, daemon=True,
                                 name=f"raft-{self.id}-{name}")
            t.start()
            self._threads.append(t)
        with self._lock:
            self._started = True
            self._spawn_replicators_locked()

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            # unblock every apply() caller promptly: after stop there is
            # no writer/apply thread left to complete them
            self._fail_waiters_locked(
                lambda: TimeoutError("raft node stopped"))
            self._apply_cond.notify_all()
            self._propose_cond.notify_all()
            self._repl_cond.notify_all()
            repls = list(self._replicators.values())
            bg = list(self._bg_threads)
        for t in self._threads + repls + bg:
            t.join(timeout=2.0)

    def _new_deadline(self) -> float:
        return time.time() + self.election_timeout * (1.0 + random.random())

    # -- public API --

    def is_leader(self) -> bool:
        with self._lock:
            return self.state == LEADER

    def apply(self, command: tuple, timeout: float = 5.0):
        """Leader-only: replicate a command, wait for commit + local
        apply, return the FSM result. Raises NotLeaderError otherwise.

        nomadload: the effective deadline is min(timeout, the request
        deadline bound at ingress); already-expired requests drop here
        instead of burning an fsync, and the owning server's admission
        controller is consulted at the propose enqueue (the proposal
        queue IS the watermark it reads)."""
        deadline = self._propose_checks(time.time() + timeout)
        if not self.batch:
            return self._apply_single(command, deadline)
        prop = _Proposal(command, deadline=deadline)
        with self._lock:
            if self._stop.is_set():
                raise TimeoutError("raft node stopped")
            if self.state != LEADER:
                raise NotLeaderError(self.leader_id)
            self._proposals.append(prop)
            self._propose_cond.notify()
        return self._await_proposal(prop, deadline)

    def _propose_checks(self, deadline: float) -> float:
        """Deadline propagation + admission at the propose boundary:
        returns the effective deadline; raises on expired work or a
        tripped watermark (loadctl.RetryLater)."""
        lc = _lc()
        bound = lc.current_deadline()
        if bound is not None:
            deadline = min(deadline, bound)
            if lc.drop_if_expired("raft_propose"):
                raise TimeoutError(
                    "request deadline passed before propose")
        adm = self.admission
        if adm is not None:
            adm.admit(lc.current_tier(), source="raft")
        return deadline

    def apply_async(self, command: tuple) -> _Proposal:
        """First half of apply (batch mode only): enqueue the command
        for the group-commit log writer and return the proposal handle
        without waiting. Proposals enter the log in apply_async call
        order, so one caller serializing its apply_async calls gets FSM
        apply order equal to its propose order — the ordering contract
        the plan applier's pipelined commit rounds depend on."""
        if not self.batch:
            raise RuntimeError("apply_async requires batch mode")
        self._propose_checks(time.time() + 3600.0)
        prop = _Proposal(command, deadline=_lc().current_deadline())
        with self._lock:
            if self._stop.is_set():
                raise TimeoutError("raft node stopped")
            if self.state != LEADER:
                raise NotLeaderError(self.leader_id)
            self._proposals.append(prop)
            self._propose_cond.notify()
        return prop

    def apply_wait(self, prop: _Proposal, timeout: float = 5.0):
        """Second half of apply_async: wait for commit + local apply,
        return the FSM result. Same timeout/step-down semantics as
        apply; safe to call at most once per proposal."""
        return self._await_proposal(prop, time.time() + timeout)

    def _apply_single(self, command: tuple, deadline: float):
        """The pre-batch write path (batch=False): one synchronous
        append + fsync under the node lock per proposal, replication
        left to the idle-heartbeat cadence. Kept as the A/B baseline
        for the group-commit rung in bench.py."""
        # Freeze the payload: callers keep mutating their structs after
        # proposing (eval status transitions, alloc updates), and a log
        # entry aliasing those objects would retransmit the MUTATED
        # payload to any follower that catches up later — replicas
        # applying different commands at the same index.
        command = copy.deepcopy(command)
        prop = _Proposal(command)
        with self._lock:
            if self._stop.is_set():
                raise TimeoutError("raft node stopped")
            if self.state != LEADER:
                raise NotLeaderError(self.leader_id)
            entry = self.log.append(self.current_term, command)
            prop.index = entry.index
            self._waiters[entry.index] = prop
            # single-node cluster commits immediately; otherwise
            # replication advances commit on acks
            self._maybe_advance_commit_locked()
        return self._await_proposal(prop, deadline)

    def _await_proposal(self, prop: _Proposal, deadline: float):
        prop.done.wait(max(0.0, deadline - time.time()))
        if not prop.done.is_set():
            with self._lock:
                # completion may have raced the timeout: every
                # completion path holds the lock, so re-check under it
                if not prop.done.is_set():
                    # unregister so the result landing later finds no
                    # waiter and is dropped instead of leaking
                    try:
                        self._proposals.remove(prop)
                    except ValueError:
                        pass
                    if prop.index is not None \
                            and self._waiters.get(prop.index) is prop:
                        del self._waiters[prop.index]
                    idx = prop.index if prop.index is not None else "?"
                    raise TimeoutError(f"apply of index {idx} timed out")
        if prop.error is not None:
            raise prop.error
        return prop.result

    def _fail_waiters_locked(self, make_err: Callable[[], BaseException]) -> None:
        """Complete every queued proposal and registered waiter with an
        error (step-down / stop). Call with the lock held."""
        stale = list(self._proposals) + list(self._waiters.values())
        self._proposals.clear()
        self._waiters.clear()
        for p in stale:
            if not p.done.is_set():
                p.error = make_err()
                p.done.set()

    # -- group commit (the log-writer thread) --

    def _run_log_writer(self) -> None:
        while not self._stop.is_set():
            with self._propose_cond:
                while not self._proposals and not self._stop.is_set():
                    self._propose_cond.wait(0.5)
                if self._stop.is_set():
                    return
                batch = self._proposals[:MAX_GROUP_COMMIT]
                del self._proposals[:MAX_GROUP_COMMIT]
            # Freeze the payloads at the propose boundary
            # (ROBUSTNESS.md): callers keep mutating their structs after
            # proposing, and a log entry aliasing them would retransmit
            # the MUTATED payload to a follower that catches up later.
            # Copying here — off the caller threads and outside the node
            # lock — is the point of the log-writer: serialization cost
            # never stalls RPC handlers or the tick thread.
            # nomadload deadline propagation: a proposal whose waiter
            # already gave up (deadline passed while queued) is dropped
            # BEFORE it costs a serialize + fsync slot — capacity spent
            # on replies nobody awaits is how overload collapses
            now = time.time()
            live = []
            for p in batch:
                if (p.deadline is not None and now >= p.deadline
                        and not p.done.is_set()):
                    _lc().check_expired(p.deadline, "raft_logwriter", now)
                    p.error = TimeoutError(
                        "proposal deadline expired before append")
                    p.done.set()
                    continue
                live.append(p)
            if not live:
                continue
            for p in live:
                p.command = copy.deepcopy(p.command)
            self._commit_batch(live)

    def _commit_batch(self, batch: List[_Proposal]) -> None:
        """Land a drained batch: one buffered write + one fsync via
        DurableLog.append_batch, outside the node lock. The append is
        CAS-guarded on the log tail: if a config entry, a new leader's
        noop, or a post-step-down truncation moved the tail while we
        were unlocked, the append refuses and we re-read the world."""
        while True:
            with self._lock:
                if self._stop.is_set() or self.state != LEADER:
                    stopped = self._stop.is_set()
                    for p in batch:
                        if not p.done.is_set():
                            p.error = (TimeoutError("raft node stopped")
                                       if stopped
                                       else NotLeaderError(self.leader_id))
                            p.done.set()
                    return
                term = self.current_term
                last_index, last_term = self.log.last()
                # register waiters BEFORE the disk write: the CAS pins
                # the indexes, and registering now means an ack that
                # races the fsync can commit + apply the entry and still
                # find its waiter. A registration that loses the CAS is
                # unregistered below; the apply loop's identity check
                # (waiter.command is entry.command) makes a stale
                # registration unable to swallow someone else's result.
                for i, p in enumerate(batch):
                    p.index = last_index + 1 + i
                    self._waiters[p.index] = p
            try:
                # the group-commit fsync: one durable write per batch
                with TRACER.span("raft.fsync", n=len(batch)):
                    entries = self.log.append_batch(
                        term, [p.command for p in batch],
                        prev=(last_index, last_term))
            except OSError as e:
                # disk fault: the log rolled the whole batch back;
                # surface the error to every caller in it
                with self._lock:
                    for p in batch:
                        if self._waiters.get(p.index) is p:
                            del self._waiters[p.index]
                        if not p.done.is_set():
                            p.error = e
                            p.done.set()
                return
            if entries is not None:
                break
            with self._lock:
                for p in batch:
                    if self._waiters.get(p.index) is p:
                        del self._waiters[p.index]
        with self._lock:
            self._maybe_advance_commit_locked()
            self._repl_cond.notify_all()

    # -- membership (reference nomad/server.go:1602 join,
    #    nomad/autopilot.go dead-server cleanup) --

    def _set_servers_locked(self, servers: Dict[str, str]) -> None:
        """Install a membership set (call with the lock held or from
        __init__). Takes effect immediately — Raft's single-server
        change rule applies configs at append, not commit."""
        self.servers = dict(servers)
        self.peers = [p for p in self.servers if p != self.id]
        for p in self.peers:
            self._next_index.setdefault(p, 1)
            self._match_index.setdefault(p, 0)
        for gone in [p for p in list(self._match_index) if p not in self.servers]:
            self._match_index.pop(gone, None)
            self._next_index.pop(gone, None)
            self._last_contact.pop(gone, None)
            self._next_heartbeat.pop(gone, None)
            self._peer_commit.pop(gone, None)
            self._repl_backoff.pop(gone, None)
        self._spawn_replicators_locked()
        if self.on_config_change is not None:
            try:
                self.on_config_change(dict(self.servers))
            except Exception:
                log.debug("on_config_change callback failed on %s",
                          self.id, exc_info=True)

    def _spawn_replicators_locked(self) -> None:
        """One replicator thread per peer (call with the lock held).
        A thread whose peer leaves the config exits on its own; a peer
        that rejoins gets a fresh thread here."""
        if not self._started or self._stop.is_set():
            return
        for p in self.peers:
            t = self._replicators.get(p)
            if t is None or not t.is_alive():
                t = threading.Thread(target=self._run_replicator, args=(p,),
                                     daemon=True,
                                     name=f"raft-{self.id}-repl-{p}")
                self._replicators[p] = t
                t.start()

    def _recover_config_from_log_locked(self, reset_on_missing: bool = False) -> None:
        base = getattr(self.log, "base_index", 0)
        last, _ = self.log.last()
        idx = base + 1
        latest = None
        while idx <= last:
            chunk = self.log.slice_from(idx)
            if not chunk:
                break
            for e in chunk:
                if tuple(e.command)[:1] == ("config",):
                    latest = (e.index, e.command[1][0])
            idx = chunk[-1].index + 1
        if latest is not None:
            self._config_index = latest[0]
            self._set_servers_locked(dict(latest[1]))
        elif reset_on_missing:
            # a truncation dropped the only config entry: the membership
            # applied at append time must revert to the snapshot /
            # bootstrap configuration, not linger
            self._config_index = 0
            self._set_servers_locked(dict(self._fallback_servers))

    def change_config(self, servers: Dict[str, str], timeout: float = 5.0):
        """Leader-only single-server membership change: append a config
        entry (effective immediately), replicate, wait for commit. One
        change at a time — a second change while the first is
        uncommitted is refused (the safety condition the one-at-a-time
        rule depends on)."""
        with self._lock:
            if self.state != LEADER:
                raise NotLeaderError(self.leader_id)
            if self._config_index > self.commit_index:
                raise ConfigInProgressError()
            cur, new = set(self.servers), set(servers)
            if len(cur.symmetric_difference(new)) > 1:
                raise ValueError("membership changes must add or remove "
                                 "one server at a time")
            entry = self.log.append(self.current_term,
                                    ("config", (dict(servers),), {}))
            self._config_index = entry.index
            self._set_servers_locked(servers)
            index = entry.index
            self._maybe_advance_commit_locked()
            self._repl_cond.notify_all()
        deadline = time.time() + timeout
        with self._apply_cond:
            while self.commit_index < index:
                if self.state != LEADER:
                    # stepped down while the change replicated — the
                    # entry may still commit under the new leader, but
                    # this node can no longer confirm it; fail fast
                    # (NotLeaderError = "outcome unknown") instead of
                    # spinning out the full timeout (nomadcheck
                    # raft_commit step-down schedule)
                    raise NotLeaderError(self.leader_id)
                remaining = deadline - time.time()
                if remaining <= 0 or self._stop.is_set():
                    raise TimeoutError(f"config change {index} timed out")
                self._apply_cond.wait(min(remaining, 0.5))

    def add_server(self, server_id: str, addr: str = "",
                   timeout: float = 5.0) -> None:
        with self._lock:
            if server_id in self.servers:
                return
            servers = dict(self.servers)
        servers[server_id] = addr
        self.change_config(servers, timeout=timeout)

    def remove_server(self, server_id: str, timeout: float = 5.0) -> None:
        if server_id == self.id:
            raise ValueError("cannot remove the current leader; "
                             "demote it by electing another first")
        with self._lock:
            if server_id not in self.servers:
                raise KeyError(f"no such server {server_id!r}")
            servers = {k: v for k, v in self.servers.items()
                       if k != server_id}
        self.change_config(servers, timeout=timeout)

    def _dead_server_cleanup(self) -> None:
        """Leader-side autopilot: remove ONE server that has been
        unreachable past the threshold, but only while the healthy
        majority stands without it (reference nomad/autopilot.go
        CleanupDeadServers)."""
        threshold = self.dead_server_cleanup_s
        now = time.time()
        with self._lock:
            if self.state != LEADER or threshold is None:
                return
            if self._config_index > self.commit_index:
                return
            healthy = 1 + sum(
                1 for p in self.peers
                if now - self._last_contact.get(p, 0.0) < threshold)
            dead = [p for p in self.peers
                    if self._last_contact.get(p) is not None
                    and now - self._last_contact[p] >= threshold]
            if not dead or healthy * 2 <= len(self.servers):
                return
            victim = dead[0]
        try:
            self.remove_server(victim, timeout=2.0)
        except (NotLeaderError, ConfigInProgressError, TimeoutError,
                ValueError, KeyError):
            pass

    # -- message handling (the RPC receiver rules) --

    def handle(self, msg: dict) -> dict:
        kind = msg["kind"]
        if kind == "request_vote":
            return self._on_request_vote(msg)
        if kind == "append_entries":
            return self._on_append_entries(msg)
        if kind == "install_snapshot":
            return self._on_install_snapshot(msg)
        raise ValueError(f"unknown raft message {kind}")

    def _persist_vote(self) -> None:
        """Term and vote must hit disk before any reply leaves this node
        (the Raft persistent-state rule)."""
        if self.stable is not None:
            self.stable.save(self.current_term, self.voted_for)

    def _on_request_vote(self, msg: dict) -> dict:
        with self._lock:
            # Leader stickiness (Raft thesis §4.2.3, hashicorp/raft's
            # check): while we hear from a live leader, a campaigner's
            # ever-growing term must not depose it — the canonical case
            # is a REMOVED server that no longer receives heartbeats and
            # campaigns forever. Non-members get no votes at all.
            recent = time.time() - self._last_leader_contact < self.election_timeout
            candidate = msg["candidate"]
            if recent or candidate not in self.servers:
                return {"term": self.current_term, "granted": False}
            term = msg["term"]
            if term > self.current_term:
                self._become_follower_locked(term)
            granted = False
            if term == self.current_term and self.voted_for in (None, msg["candidate"]):
                last_index, last_term = self.log.last()
                up_to_date = (msg["last_log_term"], msg["last_log_index"]) >= \
                    (last_term, last_index)
                if up_to_date:
                    granted = True
                    self.voted_for = msg["candidate"]
                    self._persist_vote()
                    self._deadline = self._new_deadline()
            return {"term": self.current_term, "granted": granted}

    def _conflict_hint_locked(self, prev_index: int) -> dict:
        """Follower-side catch-up hint on a prev-entry mismatch
        (hashicorp/raft / the Raft paper's fast-backtracking note):
        conflict_term is the term of our entry at prev_index and
        first_index the first index of that term, so the leader jumps a
        whole term per round trip instead of decrementing by one."""
        last_index, _ = self.log.last()
        base = getattr(self.log, "base_index", 0)
        if prev_index > last_index:
            return {"conflict_term": 0, "first_index": last_index + 1}
        ct = self.log.term_at(prev_index)
        if ct < 0:
            # prev_index fell below our snapshot base: everything up to
            # the base is committed state, resync from just past it
            return {"conflict_term": 0, "first_index": base + 1}
        fi = prev_index
        while fi - 1 > base and self.log.term_at(fi - 1) == ct:
            fi -= 1
        return {"conflict_term": ct, "first_index": fi}

    def _on_append_entries(self, msg: dict) -> dict:
        with self._lock:
            term = msg["term"]
            if term < self.current_term:
                return {"term": self.current_term, "success": False}
            if term > self.current_term or self.state != FOLLOWER:
                self._become_follower_locked(term)
            self.leader_id = msg["leader"]
            self._deadline = self._new_deadline()
            self._last_leader_contact = time.time()

            prev_index = msg["prev_log_index"]
            prev_term = msg["prev_log_term"]
            if prev_index > 0 and self.log.term_at(prev_index) != prev_term:
                reply = {"term": self.current_term, "success": False}
                reply.update(self._conflict_hint_locked(prev_index))
                return reply
            entries = [Entry(**e) if isinstance(e, dict) else e
                       for e in msg["entries"]]
            if entries:
                # the whole batch lands with a single buffered write +
                # fsync (DurableLog.append_entries) before the ack below
                truncated = self.log.append_entries(prev_index, entries)
                configs = [e for e in entries
                           if tuple(e.command)[:1] == ("config",)]
                if truncated and not configs:
                    # a dropped conflicting suffix may have contained a
                    # config entry: recompute membership from the log
                    self._recover_config_from_log_locked(reset_on_missing=True)
                elif configs:
                    last_cfg = configs[-1]
                    self._config_index = last_cfg.index
                    self._set_servers_locked(dict(last_cfg.command[1][0]))
            leader_commit = msg["leader_commit"]
            if leader_commit > self.commit_index:
                # cap at the last entry this RPC verified, not our last
                # log index: a stale divergent tail past prev+len must
                # never be committed by a leader_commit that refers to
                # the leader's (different) entries at those indexes
                new_commit = min(leader_commit, prev_index + len(entries))
                if new_commit > self.commit_index:
                    self.commit_index = new_commit
                    self._apply_cond.notify_all()
            return {"term": self.current_term,
                    "success": True,
                    "match_index": prev_index + len(entries)}

    def _on_install_snapshot(self, msg: dict) -> dict:
        """Follower-side snapshot install: the leader compacted past the
        entries this node needs (Raft §7 / hashicorp/raft InstallSnapshot).
        Chunked transfers (offset/done protocol) carry an "offset" key;
        the legacy single-frame form ships the whole dict in "data"."""
        if "offset" in msg:
            return self._on_install_snapshot_chunk(msg)
        with self._lock:
            term = msg["term"]
            if term < self.current_term:
                return {"term": self.current_term, "success": False}
            if term > self.current_term or self.state != FOLLOWER:
                self._become_follower_locked(term)
            self.leader_id = msg["leader"]
            self._deadline = self._new_deadline()
            self._last_leader_contact = time.time()
            index, snap_term = msg["index"], msg["snap_term"]
            if index <= self.last_applied:
                return {"term": self.current_term, "success": True,
                        "match_index": self.last_applied}
            if self.fsm_restore is None:
                return {"term": self.current_term, "success": False}
            try:
                self._install_locked(index, snap_term, msg["data"], None,
                                     msg.get("servers"))
            except OSError as e:
                log.warning("install_snapshot persist failed on %s: %s",
                            self.id, e)
                return {"term": self.current_term, "success": False}
            return {"term": self.current_term, "success": True,
                    "match_index": index}

    def _install_locked(self, index: int, snap_term: int, data: dict,
                        data_text: Optional[str],
                        servers: Optional[dict]) -> None:
        """Shared install tail, node lock held. Ordering is deliberate:
        persist the snapshot FIRST, then truncate the log, then mutate
        memory — a crash between any two steps leaves a state the normal
        recovery path reads back correctly (the saved snapshot's base
        makes stale log entries skippable; see DurableLog._load)."""
        if self.snapshots is not None:
            if data_text is not None:
                self.snapshots.save_raw(index, snap_term, data_text,
                                        servers=servers or self.servers)
            else:
                self.snapshots.save(index, snap_term, data,
                                    servers=servers or self.servers)
        if hasattr(self.log, "reset_to"):
            self.log.reset_to(index, snap_term)
        if servers:
            self._set_servers_locked(dict(servers))
        self.fsm_restore(data)
        self.commit_index = max(self.commit_index, index)
        self.last_applied = index
        self._apply_cond.notify_all()
        # installs can take seconds at C2M scale: restart the election
        # clock so the node doesn't immediately campaign against the
        # leader that just fed it
        self._deadline = self._new_deadline()

    def _on_install_snapshot_chunk(self, msg: dict) -> dict:
        """One frame of a chunked InstallSnapshot (Raft §7). Chunks
        accumulate in a sink (temp file beside snapshot.json when
        durable); nothing is restored until the final frame's digest
        verifies over the whole body, so a crash, disconnect, or
        leadership change mid-transfer leaves the old state intact."""
        with self._lock:
            term = msg["term"]
            if term < self.current_term:
                return {"term": self.current_term, "success": False}
            if term > self.current_term or self.state != FOLLOWER:
                self._become_follower_locked(term)
            self.leader_id = msg["leader"]
            self._deadline = self._new_deadline()
            self._last_leader_contact = time.time()
            index, snap_term = msg["index"], msg["snap_term"]
            if index <= self.last_applied:
                return {"term": self.current_term, "success": True,
                        "match_index": self.last_applied}
            if self.fsm_restore is None:
                return {"term": self.current_term, "success": False}
            rx = self._snap_rx
            if (rx is None or rx["leader"] != msg["leader"]
                    or rx["term"] != term or rx["index"] != index):
                if rx is not None:
                    rx["sink"].discard()
                sink = (self.snapshots.sink() if self.snapshots is not None
                        else MemorySnapshotSink())
                rx = self._snap_rx = {"leader": msg["leader"], "term": term,
                                      "index": index, "sink": sink}
            sink = rx["sink"]
            if msg["offset"] != sink.offset:
                # resume protocol: tell the leader where to rewind to
                return {"term": self.current_term, "success": False,
                        "offset": sink.offset}
            try:
                sink.write(msg["data"])
            except OSError as e:
                log.warning("snapshot chunk write failed on %s: %s",
                            self.id, e)
                sink.discard()
                self._snap_rx = None
                return {"term": self.current_term, "success": False,
                        "offset": 0}
            if not msg.get("done"):
                return {"term": self.current_term, "success": True,
                        "offset": sink.offset}
            self._snap_rx = None
        # final frame: verify + decode outside the lock (json.loads of a
        # C2M snapshot takes seconds; applies/heartbeats must not stall)
        text = sink.read_all()
        ok = (len(text) == msg["total"]
              and snapshot_digest(text) == msg["digest"])
        data = None
        if ok:
            try:
                data = json.loads(text)
            except ValueError:
                ok = False
        if not ok:
            log.warning("snapshot transfer to %s failed verification "
                        "(%d bytes)", self.id, len(text))
            sink.discard()
            return {"term": self.current_term, "success": False,
                    "offset": 0}
        with self._lock:
            if (msg["term"] != self.current_term or self.state != FOLLOWER
                    or index <= self.last_applied):
                sink.discard()
                return {"term": self.current_term, "success": False,
                        "offset": 0}
            try:
                self._install_locked(index, snap_term, data, text,
                                     msg.get("servers"))
            except OSError as e:
                log.warning("install_snapshot persist failed on %s: %s",
                            self.id, e)
                sink.discard()
                return {"term": self.current_term, "success": False,
                        "offset": 0}
            sink.discard()
            return {"term": self.current_term, "success": True,
                    "match_index": index}

    def _maybe_snapshot(self) -> None:
        """Apply-thread only: snapshot the FSM and compact the log once
        enough entries accumulated past the last snapshot boundary. With
        an MVCC-capable FSM (fsm_capture/fsm_serialize wired) the work
        runs on a worker thread and only the O(1) capture happens under
        the node lock; otherwise the legacy under-lock path runs."""
        if self.snapshots is None:
            return
        if not hasattr(self.log, "compact"):
            return
        if self.fsm_capture is not None and self.fsm_serialize is not None:
            return self._maybe_snapshot_async()
        if self.fsm_snapshot is None:
            return
        with self._lock:
            base = getattr(self.log, "base_index", 0)
            applied = self.last_applied
            if applied - base < self.snapshot_threshold:
                return
            term = self.log.term_at(applied)
            if term < 0:
                return
            # only this thread mutates the FSM, and holding the lock
            # blocks install_snapshot, so the dump matches `applied`
            data = self.fsm_snapshot()
            self.snapshots.save(applied, term, data, servers=self.servers)
            self.log.compact(applied, term)

    def _maybe_snapshot_async(self) -> None:
        """Stall-free variant: pin an MVCC handle + (applied, term) under
        the lock, then serialize/write/compact on a dedicated worker.
        Concurrent applies, heartbeats, and elections proceed; a CAS on
        (last_applied, base_index) discards the compaction if an
        install_snapshot raced in."""
        with self._lock:
            if self._snap_active:
                return
            base = getattr(self.log, "base_index", 0)
            applied = self.last_applied
            if applied - base < self.snapshot_threshold:
                return
            term = self.log.term_at(applied)
            if term < 0:
                return
            try:
                capture = self.fsm_capture()
            except Exception as e:
                log.warning("snapshot capture failed on %s: %s", self.id, e)
                return
            servers = dict(self.servers)
            self._snap_active = True
            t = threading.Thread(
                target=self._snapshot_worker,
                args=(capture, applied, term, servers, base),
                daemon=True, name=f"raft-{self.id}-snapshot")
            self._bg_threads = [x for x in self._bg_threads
                                if x.is_alive()] + [t]
        t.start()

    def _snapshot_worker(self, capture, applied: int, term: int,
                         servers: dict, base: int) -> None:
        try:
            with TRACER.span("raft.snapshot_persist", node=self.id,
                             index=applied):
                try:
                    data = self.fsm_serialize(capture)
                finally:
                    close = getattr(capture, "close", None)
                    if close is not None:
                        close()
                saved = self.snapshots.save(applied, term, data,
                                            servers=servers,
                                            only_if_newer=True)
            if not saved:
                return
            with self._lock:
                # CAS: an install_snapshot that raced in moved the base
                # (and possibly last_applied) — its snapshot supersedes
                # ours, so compacting to `applied` would be wrong/no-op
                if (self._stop.is_set() or self.last_applied < applied
                        or getattr(self.log, "base_index", 0) != base):
                    return
            # the log has its own lock; compacting outside the node lock
            # keeps the fsync off the commit path. A reset_to that lands
            # between the CAS and here moves base past `applied`, which
            # makes this compact a no-op inside DurableLog.
            self.log.compact(applied, term)
        except OSError as e:
            # disk fault mid-save: atomic_write left the previous
            # snapshot loadable; skip compaction and retry next round
            log.warning("snapshot persist failed on %s: %s", self.id, e)
        except Exception:
            log.exception("snapshot worker crashed on %s", self.id)
        finally:
            with self._lock:
                self._snap_active = False

    # -- roles --

    def _become_follower_locked(self, term: int) -> None:
        was_leader = self.state == LEADER
        self.state = FOLLOWER
        RECORDER.record("raft", "follower", node=self.id, term=term,
                        was_leader=was_leader)
        # Vote safety: voted_for is per-term state, so it only resets when
        # the term advances. A same-term step-down (e.g. a candidate seeing
        # the elected leader's heartbeat) must keep its recorded vote, or it
        # could grant a second vote in the same term.
        if term > self.current_term:
            self.current_term = term
            self.voted_for = None
            self._persist_vote()
        self._deadline = self._new_deadline()
        # leader-side writes can't complete any more: fail queued
        # proposals and registered waiters instead of letting callers
        # hang to their timeout (the entry may still commit under the
        # new leader — NotLeaderError means "outcome unknown", exactly
        # the old wake-time semantics)
        self._fail_waiters_locked(lambda: NotLeaderError(self.leader_id))
        # wake commit-index waiters (change_config) so they observe the
        # step-down now rather than at their next poll tick
        self._apply_cond.notify_all()
        if was_leader and self.on_leadership:
            self.on_leadership(False)

    def _become_leader_locked(self) -> None:
        self.state = LEADER
        self.leader_id = self.id
        RECORDER.record("raft", "leader", node=self.id,
                        term=self.current_term)
        last_index, _ = self.log.last()
        now = time.time()
        for p in self.peers:
            self._next_index[p] = last_index + 1
            self._match_index[p] = 0
            # autopilot clocks restart at tenure: a server that was
            # already dead before this leadership still times out and
            # gets cleaned up, and stale timestamps from an earlier
            # tenure can't condemn a healthy peer instantly
            self._last_contact[p] = now
            self._next_heartbeat[p] = 0.0
            self._peer_commit[p] = 0
            self._repl_backoff.pop(p, None)
        # Barrier entry: commit counting skips prior-term entries, so without
        # a fresh current-term entry, anything replicated under the old
        # leader stays uncommitted until the next client write. The no-op
        # commits promptly and drags predecessors with it (hashicorp/raft
        # does the same).
        self._term_start_index = self.log.append(
            self.current_term, ("noop", (), {})).index
        self._maybe_advance_commit_locked()
        self._repl_cond.notify_all()
        if self.on_leadership:
            self.on_leadership(True)

    def _start_election(self) -> None:
        with self._lock:
            self.state = CANDIDATE
            self.current_term += 1
            self.voted_for = self.id
            self._persist_vote()
            term = self.current_term
            self._deadline = self._new_deadline()
            last_index, last_term = self.log.last()
            RECORDER.record("raft", "candidate", node=self.id, term=term)
        votes = 1
        for p in self.peers:
            reply = self.transport.send(self.id, p, {
                "kind": "request_vote", "term": term, "candidate": self.id,
                "last_log_index": last_index, "last_log_term": last_term,
            })
            if reply is None:
                continue
            with self._lock:
                if reply["term"] > self.current_term:
                    self._become_follower_locked(reply["term"])
                    return
            if reply.get("granted"):
                votes += 1
        with self._lock:
            if self.state == CANDIDATE and self.current_term == term \
                    and votes * 2 > len(self.peers) + 1:
                self._become_leader_locked()

    # -- ticker (election deadlines + autopilot; replication moved to
    #    the per-peer replicator threads) --

    def _run_tick(self) -> None:
        last_cleanup = time.time()
        while not self._stop.wait(self.heartbeat_interval / 2):
            with self._lock:
                state = self.state
                expired = time.time() >= self._deadline
                # a joiner (bootstrap=False) that still only knows
                # itself must not elect itself leader of a one-node
                # cluster; it waits for the real membership
                can_elect = self.bootstrap or len(self.servers) > 1
            if state == LEADER:
                if (self.dead_server_cleanup_s is not None
                        and time.time() - last_cleanup >= 1.0):
                    last_cleanup = time.time()
                    # off-thread: remove_server blocks on commit and
                    # must not stall the tick. ONE outstanding worker:
                    # a removal blocked on commit used to leak a new
                    # thread every second on top of the stuck one.
                    t = self._autopilot
                    if t is None or not t.is_alive():
                        t = threading.Thread(
                            target=self._dead_server_cleanup,
                            daemon=True,
                            name=f"raft-{self.id}-autopilot")
                        self._autopilot = t
                        t.start()
            elif expired and can_elect:
                self._start_election()

    # -- replication (one pipelined replicator thread per peer) --

    def _repl_due_locked(self, peer: str, now: float) -> bool:
        """Does this peer need a send right now? (call with the lock
        held). True on: idle-heartbeat due, backlog to ship, or a commit
        advance the peer hasn't heard. The backoff gate keeps a dead
        peer from turning backlog into a hot retry loop."""
        if self.state != LEADER:
            return False
        if now < self._repl_backoff.get(peer, 0.0):
            return False
        if now >= self._next_heartbeat.get(peer, 0.0):
            return True
        if not self.batch:
            # pre-batch semantics (the bench baseline): replication runs
            # only at the heartbeat cadence, never woken by backlog —
            # exactly the old tick-paced _replicate_all
            return False
        if peer in self._snap_inflight:
            return False
        last_index, _ = self.log.last()
        if last_index >= self._next_index.get(peer, 1):
            return True
        return self.commit_index > self._peer_commit.get(peer, 0)

    def _run_replicator(self, peer: str) -> None:
        """Wake-on-propose replication: the log-writer (and commit
        advancement) notify _repl_cond; the timed wait is the idle-
        heartbeat fallback that replaces the old tick-paced fan-out."""
        while not self._stop.is_set():
            with self._repl_cond:
                while not self._stop.is_set() and peer in self.servers \
                        and not self._repl_due_locked(peer, time.time()):
                    self._repl_cond.wait(self.heartbeat_interval / 2)
                if self._stop.is_set():
                    return
                if peer not in self.servers:
                    # peer left the configuration; a rejoin spawns a
                    # fresh thread (_spawn_replicators_locked)
                    if self._replicators.get(peer) is threading.current_thread():
                        self._replicators.pop(peer, None)
                    return
            self._replicate(peer)

    def _replicate(self, peer: str) -> None:
        now = time.time()
        with self._lock:
            if self.state != LEADER or peer not in self.servers:
                return
            term = self.current_term
            next_idx = self._next_index.get(peer, 1)
            base = getattr(self.log, "base_index", 0)
            self._next_heartbeat[peer] = now + self.heartbeat_interval
            if next_idx <= base:
                return self._send_snapshot_locked(peer, term, base)
            prev_index = next_idx - 1
            prev_term = self.log.term_at(prev_index)
            # pre-batch mode keeps the old 64-entry default window
            window = self.max_append_entries if self.batch else 64
            entries = self.log.slice_from(next_idx, window)
            commit = self.commit_index
        # span only when entries ship — idle heartbeats would drown the
        # trace in zero-payload sends
        ctx = (TRACER.span("raft.replicate", peer=peer, n=len(entries))
               if entries else NULL_SPAN)
        with ctx:
            reply = self.transport.send(self.id, peer, {
                "kind": "append_entries", "term": term, "leader": self.id,
                "prev_log_index": prev_index, "prev_log_term": prev_term,
                "entries": [{"index": e.index, "term": e.term,
                             "command": e.command} for e in entries],
                "leader_commit": commit,
            })
        with self._lock:
            if reply is None:
                # unreachable: retry at heartbeat cadence, not hot-loop
                self._repl_backoff[peer] = time.time() + self.heartbeat_interval
                return
            if reply["term"] > self.current_term:
                self._become_follower_locked(reply["term"])
                return
            if self.state != LEADER or reply["term"] != self.current_term:
                return
            self._last_contact[peer] = time.time()
            self._repl_backoff.pop(peer, None)
            if reply["success"]:
                self._match_index[peer] = max(self._match_index.get(peer, 0),
                                              reply["match_index"])
                self._next_index[peer] = self._match_index[peer] + 1
                self._peer_commit[peer] = commit
                self._maybe_advance_commit_locked()
            else:
                self._next_index[peer] = \
                    self._conflict_next_index_locked(reply, next_idx)

    def _conflict_next_index_locked(self, reply: dict, next_idx: int) -> int:
        """Leader-side fast backtrack from a follower's conflict hint
        (call with the lock held). If we have entries of the conflicting
        term, resend from just past our last one; otherwise jump all the
        way to the follower's first index of that term. Falls back to
        decrement-by-one against a peer that sent no hint."""
        first_index = reply.get("first_index")
        if not first_index:
            return max(1, next_idx - 1)
        conflict_term = reply.get("conflict_term", 0)
        base = getattr(self.log, "base_index", 0)
        if conflict_term:
            idx = min(next_idx - 1, self.log.last()[0])
            while idx > base and self.log.term_at(idx) > conflict_term:
                idx -= 1
            if idx > base and self.log.term_at(idx) == conflict_term:
                return idx + 1
        return max(1, min(first_index, next_idx - 1))

    def _send_snapshot_locked(self, peer: str, term: int, base: int) -> None:
        """The peer needs entries the log compacted away: stream the
        snapshot in chunks instead (call with the lock held — the
        _snap_inflight reservation below relies on it; the transfer
        itself runs on a spawned thread outside the lock). At most one
        install per peer in flight — a full-state transfer outlives any
        replication round."""
        if self.snapshots is None or peer in self._snap_inflight:
            return
        self._snap_inflight.add(peer)
        t = threading.Thread(target=self._snapshot_sender, args=(peer, term),
                             daemon=True,
                             name=f"raft-{self.id}-snap-{peer}")
        self._bg_threads = [x for x in self._bg_threads
                            if x.is_alive()] + [t]
        t.start()

    def _snapshot_sender(self, peer: str, term: int) -> None:
        """Chunked InstallSnapshot transfer (Raft §7 offset/done).
        Fixed-size frames ride the "snap" transport channel; a None
        reply (peer unreachable) backs off via Retryer and resumes at
        the follower-reported offset on reconnect. Leadership loss,
        stop, or a higher term abort the transfer — the follower's
        accumulated chunks are simply superseded or discarded."""
        try:
            snap = self.snapshots.load()
            if snap is None:
                return
            index, snap_term = snap["index"], snap["term"]
            text = json.dumps(snap["data"])
            digest = snapshot_digest(text)
            total = len(text)
            with self._lock:
                servers = dict(self.servers)
            offset = 0
            with TRACER.span("raft.snapshot_send", peer=peer, index=index,
                             bytes=total):
                # each Retryer pass is one connection attempt; progress
                # resets backoff by starting a fresh Retryer
                while not self._stop.is_set():
                    retryer = Retryer(deadline_s=None, stop=self._stop,
                                      base=self.heartbeat_interval,
                                      cap=2.0)
                    progressed = False
                    for _ in retryer:
                        outcome, offset = self._push_snapshot_chunks(
                            peer, term, index, snap_term, text, digest,
                            total, servers, offset)
                        if outcome == "done":
                            return
                        if outcome == "progress":
                            progressed = True
                            break  # fresh Retryer → backoff resets
                    if not progressed:
                        return
        except Exception:
            log.exception("snapshot sender to %s crashed", peer)
        finally:
            with self._lock:
                self._snap_inflight.discard(peer)

    def _push_snapshot_chunks(self, peer: str, term: int, index: int,
                              snap_term: int, text: str, digest: str,
                              total: int, servers: dict, offset: int):
        """Send frames from `offset` until the transfer completes, the
        peer rewinds us, or the peer stops answering. Returns
        (outcome, next_offset): "done" = finished or aborted for good,
        "progress" = at least one frame landed before a None reply
        (caller resets backoff), "retry" = unreachable with no
        progress."""
        chunk = self.snapshot_chunk_bytes
        made_progress = False
        while True:
            with self._lock:
                if (self._stop.is_set() or self.state != LEADER
                        or self.current_term != term):
                    return "done", offset
            done = offset + chunk >= total
            msg = {"kind": "install_snapshot", "term": term,
                   "leader": self.id, "index": index,
                   "snap_term": snap_term, "offset": offset,
                   "data": text[offset:offset + chunk], "done": done}
            if done:
                msg["total"] = total
                msg["digest"] = digest
                msg["servers"] = servers
            reply = self.transport.send(self.id, peer, msg)
            if reply is None:
                return ("progress" if made_progress else "retry"), offset
            with self._lock:
                if reply["term"] > self.current_term:
                    self._become_follower_locked(reply["term"])
                    return "done", offset
                if self.state != LEADER or self.current_term != term:
                    return "done", offset
                self._last_contact[peer] = time.time()
                if reply.get("success"):
                    if "match_index" in reply:
                        # follower finished the install (or already had
                        # this index)
                        self._match_index[peer] = max(
                            self._match_index.get(peer, 0),
                            reply["match_index"])
                        self._next_index[peer] = self._match_index[peer] + 1
                        self._maybe_advance_commit_locked()
                        return "done", offset
                    offset = reply.get("offset", offset + len(msg["data"]))
                    made_progress = True
                    continue
                if "offset" in reply:
                    # resume protocol: realign to where the follower is.
                    # A rewind that makes no net progress (e.g. a disk
                    # fault reset the sink to 0) backs off via the
                    # caller's Retryer instead of hot-looping.
                    new_off = reply["offset"]
                    forward = new_off > offset
                    offset = new_off
                    if forward or made_progress:
                        made_progress = True
                        continue
                    return "retry", offset
                # hard refusal (no fsm_restore, stale term view): give up
                return "done", offset

    def _maybe_advance_commit_locked(self) -> None:
        """Quorum commit via one sorted match-index pass (call with the
        lock held). The median-ish element of the descending-sorted
        match vector IS the highest index a majority holds; one
        current-term check suffices because terms are monotone in index —
        if the quorum index carries an older term, no current-term entry
        is quorum-replicated yet (the leader barrier noop closes that
        window at term start)."""
        if self.state != LEADER:
            return
        last_index, _ = self.log.last()
        matches = [last_index]  # the leader's own durable log
        matches.extend(self._match_index.get(p, 0) for p in self.peers)
        matches.sort(reverse=True)
        n = matches[len(matches) // 2]
        if n > self.commit_index and self.log.term_at(n) == self.current_term:
            self.commit_index = n
            self._apply_cond.notify_all()
            # piggyback the new commit index to followers promptly so
            # their FSMs converge without waiting for the idle heartbeat
            self._repl_cond.notify_all()

    # -- apply loop --

    def _run_apply(self) -> None:
        while not self._stop.is_set():
            with self._apply_cond:
                while self.last_applied >= self.commit_index \
                        and not self._stop.is_set():
                    self._apply_cond.wait(0.5)
            if self._stop.is_set():
                return
            while self._apply_chunk():
                pass
            self._maybe_snapshot()

    def _apply_chunk(self) -> bool:
        """Apply up to APPLY_CHUNK committed entries under ONE lock hold
        and wake all waiters with ONE notify_all. The re-check, fetch,
        and FSM mutation stay a single critical section with
        _on_install_snapshot (RPC thread): releasing the lock between
        the last_applied check and fsm_apply would let a snapshot
        restore land in between, after which applying the stale entry
        regresses the restored store. The chunk bound keeps RPC handlers
        from stalling behind an arbitrarily large committed backlog."""
        with self._lock:
            start = self.last_applied + 1
            end = min(self.commit_index, start + APPLY_CHUNK - 1)
            if start > end:
                return False
            with TRACER.span("raft.apply", n=end - start + 1,
                             node=self.id):
                for idx in range(start, end + 1):
                    entry = self.log.get(idx)
                    if entry is None:
                        break  # compacted/leapfrogged: recompute next round
                    if tuple(entry.command)[:1] in (("noop",), ("config",)):
                        result = None  # raft-internal entries, not FSM ops
                    else:
                        try:
                            result = self.fsm_apply(tuple(entry.command))
                        except Exception as e:
                            result = e
                    self.last_applied = idx
                    waiter = self._waiters.get(idx)
                    if waiter is not None \
                            and waiter.command is entry.command:
                        # identity check: a registration that lost the
                        # append CAS must not swallow another entry's
                        # result
                        del self._waiters[idx]
                        waiter.result = result
                        waiter.done.set()
            progressed = self.last_applied >= start
            self._apply_cond.notify_all()
        return progressed

    # -- read path (read-index / lease; Raft §6.4) --

    def wait_applied(self, index: int, timeout: float = 5.0) -> None:
        """Block until this node's FSM has applied through the given
        RAFT log index (the second half of a follower read: the leader
        names a read index, the serving node waits to reach it). Note
        the raft index space counts noop/config entries — it is NOT the
        state store's MVCC index."""
        deadline = time.monotonic() + timeout
        with self._apply_cond:
            while self.last_applied < index:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._stop.is_set():
                    raise TimeoutError(
                        f"fsm at {self.last_applied}, read index {index}")
                self._apply_cond.wait(min(remaining, 0.05))

    def last_contact_age(self) -> float:
        """Seconds since this node last heard from a live leader — the
        HTTP layer's X-Nomad-LastContact bound. 0.0 on the leader (it IS
        the source), inf when no leader was ever heard."""
        with self._lock:
            if self.state == LEADER:
                return 0.0
            if self._last_leader_contact <= 0.0:
                return float("inf")
            return max(0.0, time.time() - self._last_leader_contact)

    def _lease_valid_locked(self, now: float) -> bool:
        """True while a quorum of the cluster acked this leader within
        lease_duration (call with the lock held). The leader counts
        toward its own quorum, so it needs quorum-1 recent peer acks."""
        peers = self.peers
        if not peers:
            return True
        need = (len(peers) + 1) // 2 + 1 - 1  # quorum minus self
        recent = sum(1 for p in peers
                     if now - self._last_contact.get(p, 0.0)
                     < self.lease_duration)
        return recent >= need

    def read_index(self, timeout: float = 1.0, lease: bool = True) -> int:
        """Leader-side half of a linearizable read: confirm we are still
        the leader, then return a commit index the reader must wait past
        (serve once ``last_applied >= read_index`` on ANY server).

        Confirmation is a held lease (quorum of replication acks within
        lease_duration) when ``lease=True``, else a full round of empty
        append_entries (``lease=False`` = the ?consistent= HTTP mode —
        immune even to clock-rate assumptions). Either way the read
        index is only valid once this term's barrier noop has committed:
        before that, entries committed by the previous leader are not
        yet known final (Raft §8), so we first wait for it.

        Raises NotLeaderError when not (or no longer provably) the
        leader, TimeoutError when the barrier noop doesn't commit in
        time (e.g. a freshly elected leader still replicating)."""
        deadline = time.monotonic() + timeout
        with self._lock:
            if self._stop.is_set():
                # a stopped (crashed) node may still carry LEADER state;
                # it must never vouch for a read
                raise NotLeaderError(None)
            if self.state != LEADER:
                raise NotLeaderError(self.leader_id)
            term = self.current_term
            # wait for the term-start barrier to commit
            while self.commit_index < self._term_start_index:
                if self.state != LEADER or self.current_term != term \
                        or self._stop.is_set():
                    raise NotLeaderError(self.leader_id)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError("term-start barrier not committed")
                self._apply_cond.wait(min(remaining, 0.05))
            index = self.commit_index
            if lease and self._lease_valid_locked(time.time()):
                _registry().incr("nomad.reads.lease_reads")
                return index
        # no valid lease (or caller opted out): prove leadership with a
        # round of empty append_entries — outside the lock, it's I/O
        self._confirm_leadership(term, deadline)
        return index

    def _confirm_leadership(self, term: int, deadline: float) -> None:
        """One empty-AppendEntries round: a quorum answering in our term
        proves no newer leader exists (their acks double as fresh lease
        basis). Raises NotLeaderError on a higher term or no quorum."""
        with self._lock:
            if self.state != LEADER or self.current_term != term:
                raise NotLeaderError(self.leader_id)
            peers = list(self.peers)
            last_index, _ = self.log.last()
            prev_term = self.log.term_at(last_index)
            commit = self.commit_index
        acks = 1  # self
        for p in peers:
            if time.monotonic() > deadline:
                break
            reply = self.transport.send(self.id, p, {
                "kind": "append_entries", "term": term, "leader": self.id,
                "prev_log_index": last_index, "prev_log_term": prev_term,
                "entries": [], "leader_commit": commit,
            })
            if reply is None:
                continue
            with self._lock:
                if reply["term"] > self.current_term:
                    self._become_follower_locked(reply["term"])
                    raise NotLeaderError(self.leader_id)
                if reply["term"] == term:
                    # success or not, a same-term reply acknowledges our
                    # leadership (a log mismatch is a replication
                    # problem, not an authority one)
                    acks += 1
                    self._last_contact[p] = time.time()
        with self._lock:
            if self.state != LEADER or self.current_term != term:
                raise NotLeaderError(self.leader_id)
        if acks * 2 <= len(peers) + 1:
            raise NotLeaderError(None)
        _registry().incr("nomad.reads.lease_extensions")


def _registry():
    """Lazy: core.metrics is standalone, but importing it at module load
    would pull core/__init__ -> server -> raft while raft is mid-load."""
    global _REG
    if _REG is None:
        from ..core.metrics import REGISTRY
        _REG = REGISTRY
    return _REG


_REG = None


class NotLeaderError(Exception):
    def __init__(self, leader_id: Optional[str]):
        super().__init__(f"not the leader (leader: {leader_id})")
        self.leader_id = leader_id


class ConfigInProgressError(Exception):
    def __init__(self):
        super().__init__("a membership change is already in flight")
