"""The Raft state machine (leader election + log replication).

Follows the Raft paper's receiver/sender rules: randomized election
timeouts, term-based vote safety with the up-to-date log check, leader
append-entries with per-peer next/match indexes, and commit advancement
restricted to current-term entries. Committed commands are applied to
the FSM in log order on a dedicated apply thread; leader-side apply()
blocks until the entry is both committed and locally applied, giving
the linearizable write the plan applier needs.
"""

from __future__ import annotations

import copy
import logging
import random
import threading
import time
from typing import Callable, Dict, List, Optional

from .log import Entry, RaftLog

log = logging.getLogger("nomad_tpu.raft")

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"


class RaftNode:
    def __init__(self, node_id: str, peers: List[str], transport,
                 fsm_apply: Callable[[tuple], object],
                 election_timeout: float = 0.3,
                 heartbeat_interval: float = 0.05,
                 on_leadership: Optional[Callable[[bool], None]] = None,
                 log=None, stable=None, snapshots=None,
                 fsm_snapshot: Optional[Callable[[], dict]] = None,
                 fsm_restore: Optional[Callable[[dict], None]] = None,
                 snapshot_threshold: int = 1024,
                 peer_addrs: Optional[Dict[str, str]] = None,
                 on_config_change: Optional[Callable[[Dict[str, str]], None]] = None,
                 bootstrap: bool = True,
                 dead_server_cleanup_s: Optional[float] = None):
        self.id = node_id
        # membership: server id -> address ("" when the transport
        # resolves ids directly). Config-change log entries rewrite this
        # at APPEND time (the standard single-server-change rule; see
        # change_config) — reference nomad/server.go AddVoter/
        # RemoveServer via hashicorp/raft.
        self.servers: Dict[str, str] = {node_id: (peer_addrs or {}).get(node_id, "")}
        for p in peers:
            if p != node_id:
                self.servers[p] = (peer_addrs or {}).get(p, "")
        self.peers = [p for p in self.servers if p != node_id]
        self.on_config_change = on_config_change
        # a non-bootstrap node with no peers (a joiner) must NOT elect
        # itself leader of a one-node cluster; it waits to learn the
        # real membership from the leader's append_entries
        self.bootstrap = bootstrap
        self.dead_server_cleanup_s = dead_server_cleanup_s
        self._last_contact: Dict[str, float] = {}
        self._config_index = 0  # log index of the latest config entry
        # replication state precedes the durability restore below:
        # a recovered snapshot/log config calls _set_servers_locked, which
        # maintains these
        self._next_index: Dict[str, int] = {}
        self._match_index: Dict[str, int] = {}
        self.transport = transport
        self.fsm_apply = fsm_apply
        self.on_leadership = on_leadership
        self.election_timeout = election_timeout
        self.heartbeat_interval = heartbeat_interval

        self.state = FOLLOWER
        self.current_term = 0
        self.voted_for: Optional[str] = None
        self.log = log if log is not None else RaftLog()
        self.commit_index = 0
        self.last_applied = 0
        self.leader_id: Optional[str] = None

        # durability (raft/durable.py); all optional — in-memory otherwise
        self.stable = stable
        self.snapshots = snapshots
        self.fsm_snapshot = fsm_snapshot
        self.fsm_restore = fsm_restore
        self.snapshot_threshold = snapshot_threshold
        if stable is not None:
            self.current_term = stable.term
            self.voted_for = stable.voted_for
        if snapshots is not None and fsm_restore is not None:
            snap = snapshots.load()
            if snap is not None:
                fsm_restore(snap["data"])
                self.commit_index = snap["index"]
                self.last_applied = snap["index"]
                if snap.get("servers"):
                    self._set_servers_locked(dict(snap["servers"]))
        # the config to fall back to if a log truncation drops the only
        # config entry (snapshot membership, else the bootstrap peers)
        self._fallback_servers = dict(self.servers)
        # membership survives restarts: the latest config entry in the
        # recovered log wins over the snapshot's
        self._recover_config_from_log_locked()
        self._last_leader_contact = 0.0

        self._snap_inflight: set = set()  # peers mid-install-snapshot
        self._lock = threading.RLock()
        self._apply_cond = threading.Condition(self._lock)
        self._deadline = self._new_deadline()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        # apply results by index for leader-side waiters
        self._results: Dict[int, object] = {}

        transport.register(node_id, self.handle)

    # -- lifecycle --

    def start(self) -> None:
        for name, fn in (("tick", self._run_tick), ("apply", self._run_apply)):
            t = threading.Thread(target=fn, daemon=True,
                                 name=f"raft-{self.id}-{name}")
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        with self._apply_cond:
            self._apply_cond.notify_all()
        for t in self._threads:
            t.join(timeout=2.0)

    def _new_deadline(self) -> float:
        return time.time() + self.election_timeout * (1.0 + random.random())

    # -- public API --

    def is_leader(self) -> bool:
        with self._lock:
            return self.state == LEADER

    def apply(self, command: tuple, timeout: float = 5.0):
        """Leader-only: replicate a command, wait for commit + local
        apply, return the FSM result. Raises NotLeaderError otherwise."""
        # Freeze the payload: callers keep mutating their structs after
        # proposing (eval status transitions, alloc updates), and a log
        # entry aliasing those objects would retransmit the MUTATED
        # payload to any follower that catches up later — replicas
        # applying different commands at the same index.
        command = copy.deepcopy(command)
        with self._lock:
            if self.state != LEADER:
                raise NotLeaderError(self.leader_id)
            entry = self.log.append(self.current_term, command)
            index = entry.index
        # single-node cluster commits immediately; otherwise replication
        # advances commit on acks
        self._maybe_advance_commit()
        deadline = time.time() + timeout
        with self._apply_cond:
            while self.last_applied < index:
                remaining = deadline - time.time()
                if remaining <= 0 or self._stop.is_set():
                    raise TimeoutError(f"apply of index {index} timed out")
                self._apply_cond.wait(min(remaining, 0.1))
            if self.state != LEADER:
                raise NotLeaderError(self.leader_id)
            return self._results.pop(index, None)

    # -- membership (reference nomad/server.go:1602 join,
    #    nomad/autopilot.go dead-server cleanup) --

    def _set_servers_locked(self, servers: Dict[str, str]) -> None:
        """Install a membership set (call with the lock held or from
        __init__). Takes effect immediately — Raft's single-server
        change rule applies configs at append, not commit."""
        self.servers = dict(servers)
        self.peers = [p for p in self.servers if p != self.id]
        for p in self.peers:
            self._next_index.setdefault(p, 1)
            self._match_index.setdefault(p, 0)
        for gone in [p for p in list(self._match_index) if p not in self.servers]:
            self._match_index.pop(gone, None)
            self._next_index.pop(gone, None)
            self._last_contact.pop(gone, None)
        if self.on_config_change is not None:
            try:
                self.on_config_change(dict(self.servers))
            except Exception:
                log.debug("on_config_change callback failed on %s",
                          self.id, exc_info=True)

    def _recover_config_from_log_locked(self, reset_on_missing: bool = False) -> None:
        base = getattr(self.log, "base_index", 0)
        last, _ = self.log.last()
        idx = base + 1
        latest = None
        while idx <= last:
            chunk = self.log.slice_from(idx)
            if not chunk:
                break
            for e in chunk:
                if tuple(e.command)[:1] == ("config",):
                    latest = (e.index, e.command[1][0])
            idx = chunk[-1].index + 1
        if latest is not None:
            self._config_index = latest[0]
            self._set_servers_locked(dict(latest[1]))
        elif reset_on_missing:
            # a truncation dropped the only config entry: the membership
            # applied at append time must revert to the snapshot /
            # bootstrap configuration, not linger
            self._config_index = 0
            self._set_servers_locked(dict(self._fallback_servers))

    def change_config(self, servers: Dict[str, str], timeout: float = 5.0):
        """Leader-only single-server membership change: append a config
        entry (effective immediately), replicate, wait for commit. One
        change at a time — a second change while the first is
        uncommitted is refused (the safety condition the one-at-a-time
        rule depends on)."""
        with self._lock:
            if self.state != LEADER:
                raise NotLeaderError(self.leader_id)
            if self._config_index > self.commit_index:
                raise ConfigInProgressError()
            cur, new = set(self.servers), set(servers)
            if len(cur.symmetric_difference(new)) > 1:
                raise ValueError("membership changes must add or remove "
                                 "one server at a time")
            entry = self.log.append(self.current_term,
                                    ("config", (dict(servers),), {}))
            self._config_index = entry.index
            self._set_servers_locked(servers)
            index = entry.index
        self._maybe_advance_commit()
        deadline = time.time() + timeout
        with self._apply_cond:
            while self.commit_index < index:
                remaining = deadline - time.time()
                if remaining <= 0 or self._stop.is_set():
                    raise TimeoutError(f"config change {index} timed out")
                self._apply_cond.wait(min(remaining, 0.1))

    def add_server(self, server_id: str, addr: str = "",
                   timeout: float = 5.0) -> None:
        with self._lock:
            if server_id in self.servers:
                return
            servers = dict(self.servers)
        servers[server_id] = addr
        self.change_config(servers, timeout=timeout)

    def remove_server(self, server_id: str, timeout: float = 5.0) -> None:
        if server_id == self.id:
            raise ValueError("cannot remove the current leader; "
                             "demote it by electing another first")
        with self._lock:
            if server_id not in self.servers:
                raise KeyError(f"no such server {server_id!r}")
            servers = {k: v for k, v in self.servers.items()
                       if k != server_id}
        self.change_config(servers, timeout=timeout)

    def _dead_server_cleanup(self) -> None:
        """Leader-side autopilot: remove ONE server that has been
        unreachable past the threshold, but only while the healthy
        majority stands without it (reference nomad/autopilot.go
        CleanupDeadServers)."""
        threshold = self.dead_server_cleanup_s
        now = time.time()
        with self._lock:
            if self.state != LEADER or threshold is None:
                return
            if self._config_index > self.commit_index:
                return
            healthy = 1 + sum(
                1 for p in self.peers
                if now - self._last_contact.get(p, 0.0) < threshold)
            dead = [p for p in self.peers
                    if self._last_contact.get(p) is not None
                    and now - self._last_contact[p] >= threshold]
            if not dead or healthy * 2 <= len(self.servers):
                return
            victim = dead[0]
        try:
            self.remove_server(victim, timeout=2.0)
        except (NotLeaderError, ConfigInProgressError, TimeoutError,
                ValueError, KeyError):
            pass

    # -- message handling (the RPC receiver rules) --

    def handle(self, msg: dict) -> dict:
        kind = msg["kind"]
        if kind == "request_vote":
            return self._on_request_vote(msg)
        if kind == "append_entries":
            return self._on_append_entries(msg)
        if kind == "install_snapshot":
            return self._on_install_snapshot(msg)
        raise ValueError(f"unknown raft message {kind}")

    def _persist_vote(self) -> None:
        """Term and vote must hit disk before any reply leaves this node
        (the Raft persistent-state rule)."""
        if self.stable is not None:
            self.stable.save(self.current_term, self.voted_for)

    def _on_request_vote(self, msg: dict) -> dict:
        with self._lock:
            # Leader stickiness (Raft thesis §4.2.3, hashicorp/raft's
            # check): while we hear from a live leader, a campaigner's
            # ever-growing term must not depose it — the canonical case
            # is a REMOVED server that no longer receives heartbeats and
            # campaigns forever. Non-members get no votes at all.
            recent = time.time() - self._last_leader_contact < self.election_timeout
            candidate = msg["candidate"]
            if recent or candidate not in self.servers:
                return {"term": self.current_term, "granted": False}
            term = msg["term"]
            if term > self.current_term:
                self._become_follower_locked(term)
            granted = False
            if term == self.current_term and self.voted_for in (None, msg["candidate"]):
                last_index, last_term = self.log.last()
                up_to_date = (msg["last_log_term"], msg["last_log_index"]) >= \
                    (last_term, last_index)
                if up_to_date:
                    granted = True
                    self.voted_for = msg["candidate"]
                    self._persist_vote()
                    self._deadline = self._new_deadline()
            return {"term": self.current_term, "granted": granted}

    def _on_append_entries(self, msg: dict) -> dict:
        with self._lock:
            term = msg["term"]
            if term < self.current_term:
                return {"term": self.current_term, "success": False}
            if term > self.current_term or self.state != FOLLOWER:
                self._become_follower_locked(term)
            self.leader_id = msg["leader"]
            self._deadline = self._new_deadline()
            self._last_leader_contact = time.time()

            prev_index = msg["prev_log_index"]
            prev_term = msg["prev_log_term"]
            if prev_index > 0 and self.log.term_at(prev_index) != prev_term:
                return {"term": self.current_term, "success": False}
            entries = [Entry(**e) if isinstance(e, dict) else e
                       for e in msg["entries"]]
            if entries:
                truncated = self.log.append_entries(prev_index, entries)
                configs = [e for e in entries
                           if tuple(e.command)[:1] == ("config",)]
                if truncated and not configs:
                    # a dropped conflicting suffix may have contained a
                    # config entry: recompute membership from the log
                    self._recover_config_from_log_locked(reset_on_missing=True)
                elif configs:
                    last_cfg = configs[-1]
                    self._config_index = last_cfg.index
                    self._set_servers_locked(dict(last_cfg.command[1][0]))
            leader_commit = msg["leader_commit"]
            if leader_commit > self.commit_index:
                last_index, _ = self.log.last()
                self.commit_index = min(leader_commit, last_index)
                self._apply_cond.notify_all()
            return {"term": self.current_term,
                    "success": True,
                    "match_index": prev_index + len(entries)}

    def _on_install_snapshot(self, msg: dict) -> dict:
        """Follower-side snapshot install: the leader compacted past the
        entries this node needs (Raft §7 / hashicorp/raft InstallSnapshot)."""
        with self._lock:
            term = msg["term"]
            if term < self.current_term:
                return {"term": self.current_term, "success": False}
            if term > self.current_term or self.state != FOLLOWER:
                self._become_follower_locked(term)
            self.leader_id = msg["leader"]
            self._deadline = self._new_deadline()
            self._last_leader_contact = time.time()
            index, snap_term = msg["index"], msg["snap_term"]
            if index <= self.last_applied:
                return {"term": self.current_term, "success": True,
                        "match_index": self.last_applied}
            if self.fsm_restore is None:
                return {"term": self.current_term, "success": False}
            self.fsm_restore(msg["data"])
            if hasattr(self.log, "reset_to"):
                self.log.reset_to(index, snap_term)
            if msg.get("servers"):
                self._set_servers_locked(dict(msg["servers"]))
            if self.snapshots is not None:
                self.snapshots.save(index, snap_term, msg["data"],
                                    servers=self.servers)
            self.commit_index = max(self.commit_index, index)
            self.last_applied = index
            self._apply_cond.notify_all()
            return {"term": self.current_term, "success": True,
                    "match_index": index}

    def _maybe_snapshot(self) -> None:
        """Apply-thread only: snapshot the FSM and compact the log once
        enough entries accumulated past the last snapshot boundary. Runs
        under the node lock so a concurrent install_snapshot (RPC thread)
        can't interleave and leave an older-labeled snapshot covering
        newer state."""
        if self.snapshots is None or self.fsm_snapshot is None:
            return
        if not hasattr(self.log, "compact"):
            return
        with self._lock:
            base = getattr(self.log, "base_index", 0)
            applied = self.last_applied
            if applied - base < self.snapshot_threshold:
                return
            term = self.log.term_at(applied)
            if term < 0:
                return
            # only this thread mutates the FSM, and holding the lock
            # blocks install_snapshot, so the dump matches `applied`
            data = self.fsm_snapshot()
            self.snapshots.save(applied, term, data, servers=self.servers)
            self.log.compact(applied, term)

    # -- roles --

    def _become_follower_locked(self, term: int) -> None:
        was_leader = self.state == LEADER
        self.state = FOLLOWER
        # Vote safety: voted_for is per-term state, so it only resets when
        # the term advances. A same-term step-down (e.g. a candidate seeing
        # the elected leader's heartbeat) must keep its recorded vote, or it
        # could grant a second vote in the same term.
        if term > self.current_term:
            self.current_term = term
            self.voted_for = None
            self._persist_vote()
        self._deadline = self._new_deadline()
        if was_leader and self.on_leadership:
            self.on_leadership(False)

    def _become_leader_locked(self) -> None:
        self.state = LEADER
        self.leader_id = self.id
        last_index, _ = self.log.last()
        now = time.time()
        for p in self.peers:
            self._next_index[p] = last_index + 1
            self._match_index[p] = 0
            # autopilot clocks restart at tenure: a server that was
            # already dead before this leadership still times out and
            # gets cleaned up, and stale timestamps from an earlier
            # tenure can't condemn a healthy peer instantly
            self._last_contact[p] = now
        # Barrier entry: commit counting skips prior-term entries, so without
        # a fresh current-term entry, anything replicated under the old
        # leader stays uncommitted until the next client write. The no-op
        # commits promptly and drags predecessors with it (hashicorp/raft
        # does the same).
        self.log.append(self.current_term, ("noop", (), {}))
        if self.on_leadership:
            self.on_leadership(True)

    def _start_election(self) -> None:
        with self._lock:
            self.state = CANDIDATE
            self.current_term += 1
            self.voted_for = self.id
            self._persist_vote()
            term = self.current_term
            self._deadline = self._new_deadline()
            last_index, last_term = self.log.last()
        votes = 1
        for p in self.peers:
            reply = self.transport.send(self.id, p, {
                "kind": "request_vote", "term": term, "candidate": self.id,
                "last_log_index": last_index, "last_log_term": last_term,
            })
            if reply is None:
                continue
            with self._lock:
                if reply["term"] > self.current_term:
                    self._become_follower_locked(reply["term"])
                    return
            if reply.get("granted"):
                votes += 1
        with self._lock:
            if self.state == CANDIDATE and self.current_term == term \
                    and votes * 2 > len(self.peers) + 1:
                self._become_leader_locked()

    # -- ticker --

    def _run_tick(self) -> None:
        last_cleanup = time.time()
        while not self._stop.wait(self.heartbeat_interval / 2):
            with self._lock:
                state = self.state
                expired = time.time() >= self._deadline
                # a joiner (bootstrap=False) that still only knows
                # itself must not elect itself leader of a one-node
                # cluster; it waits for the real membership
                can_elect = self.bootstrap or len(self.servers) > 1
            if state == LEADER:
                self._replicate_all()
                if (self.dead_server_cleanup_s is not None
                        and time.time() - last_cleanup >= 1.0):
                    last_cleanup = time.time()
                    # off-thread: remove_server blocks on commit and
                    # must not stall the heartbeat fan-out
                    threading.Thread(target=self._dead_server_cleanup,
                                     daemon=True,
                                     name=f"raft-{self.id}-autopilot").start()
            elif expired and can_elect:
                self._start_election()

    def _replicate_all(self) -> None:
        for p in self.peers:
            self._replicate(p)
        self._maybe_advance_commit()

    def _replicate(self, peer: str) -> None:
        with self._lock:
            if self.state != LEADER:
                return
            term = self.current_term
            next_idx = self._next_index.get(peer, 1)
            base = getattr(self.log, "base_index", 0)
            if next_idx <= base:
                return self._send_snapshot(peer, term, base)
            prev_index = next_idx - 1
            prev_term = self.log.term_at(prev_index)
            entries = self.log.slice_from(next_idx)
            commit = self.commit_index
        reply = self.transport.send(self.id, peer, {
            "kind": "append_entries", "term": term, "leader": self.id,
            "prev_log_index": prev_index, "prev_log_term": prev_term,
            "entries": [{"index": e.index, "term": e.term, "command": e.command}
                        for e in entries],
            "leader_commit": commit,
        })
        if reply is None:
            return
        with self._lock:
            if reply["term"] > self.current_term:
                self._become_follower_locked(reply["term"])
                return
            if self.state != LEADER or reply["term"] != self.current_term:
                return
            self._last_contact[peer] = time.time()
            if reply["success"]:
                self._match_index[peer] = max(self._match_index.get(peer, 0),
                                              reply["match_index"])
                self._next_index[peer] = self._match_index[peer] + 1
            else:
                self._next_index[peer] = max(1, next_idx - 1)

    def _send_snapshot(self, peer: str, term: int, base: int) -> None:
        """The peer needs entries the log compacted away: ship the whole
        snapshot instead (called with the lock held; sends outside it).
        At most one install per peer in flight — replication ticks fire
        every heartbeat and a full-state transfer outlives them."""
        if self.snapshots is None or peer in self._snap_inflight:
            return
        self._snap_inflight.add(peer)

        def send():
            try:
                snap = self.snapshots.load()
                if snap is None:
                    return
                reply = self.transport.send(self.id, peer, {
                    "kind": "install_snapshot", "term": term,
                    "leader": self.id, "index": snap["index"],
                    "snap_term": snap["term"], "data": snap["data"],
                    "servers": dict(self.servers),
                })
                if reply is None:
                    return
                with self._lock:
                    if reply["term"] > self.current_term:
                        self._become_follower_locked(reply["term"])
                        return
                    if self.state != LEADER:
                        return
                    if reply.get("success"):
                        self._match_index[peer] = max(
                            self._match_index.get(peer, 0),
                            reply["match_index"])
                        self._next_index[peer] = self._match_index[peer] + 1
            finally:
                with self._lock:
                    self._snap_inflight.discard(peer)

        threading.Thread(target=send, daemon=True,
                         name=f"raft-{self.id}-snap-{peer}").start()

    def _maybe_advance_commit(self) -> None:
        with self._lock:
            if self.state != LEADER:
                return
            last_index, _ = self.log.last()
            for n in range(last_index, self.commit_index, -1):
                if self.log.term_at(n) != self.current_term:
                    break  # only current-term entries commit by counting
                acks = 1 + sum(1 for p in self.peers
                               if self._match_index.get(p, 0) >= n)
                if acks * 2 > len(self.peers) + 1:
                    self.commit_index = n
                    self._apply_cond.notify_all()
                    break

    # -- apply loop --

    def _run_apply(self) -> None:
        while not self._stop.is_set():
            with self._apply_cond:
                while self.last_applied >= self.commit_index:
                    self._apply_cond.wait(0.1)
                    if self._stop.is_set():
                        return
                start = self.last_applied + 1
                end = self.commit_index
            for idx in range(start, end + 1):
                # The re-check, fetch, and FSM mutation must be one
                # critical section with _on_install_snapshot (RPC thread):
                # releasing the lock between the last_applied check and
                # fsm_apply would let a snapshot restore land in between,
                # after which applying the stale entry regresses the
                # restored store. Same discipline _maybe_snapshot uses.
                with self._lock:
                    if idx <= self.last_applied:
                        continue  # an install_snapshot leapfrogged us
                    entry = self.log.get(idx)
                    if entry is None:
                        break
                    if tuple(entry.command)[:1] in (("noop",), ("config",)):
                        result = None  # raft-internal entries, not FSM ops
                    else:
                        try:
                            result = self.fsm_apply(tuple(entry.command))
                        except Exception as e:
                            result = e
                with self._apply_cond:
                    self._results[idx] = result
                    if len(self._results) > 4096:
                        # drop results nobody waited for
                        for k in sorted(self._results)[:-1024]:
                            self._results.pop(k, None)
                    self.last_applied = max(self.last_applied, idx)
                    self._apply_cond.notify_all()
            self._maybe_snapshot()


class NotLeaderError(Exception):
    def __init__(self, leader_id: Optional[str]):
        super().__init__(f"not the leader (leader: {leader_id})")
        self.leader_id = leader_id


class ConfigInProgressError(Exception):
    def __init__(self):
        super().__init__("a membership change is already in flight")
