"""Raft transport (reference nomad/raft_rpc.go over yamux TCP).

The node logic only needs `send(peer, message) -> reply`. The in-process
transport used by tests and single-host multi-server setups dispatches
directly; a socket transport carrying the same dict messages slots in
for multi-host (message schema is JSON-safe by construction).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional


class InProcTransport:
    """A registry of node handlers; send() is a function call with a
    configurable failure set for partition tests."""

    def __init__(self):
        self._handlers: Dict[str, Callable[[dict], dict]] = {}
        self._lock = threading.Lock()
        self._partitioned: set = set()  # node ids cut off from everyone

    def register(self, node_id: str, handler: Callable[[dict], dict]) -> None:
        with self._lock:
            self._handlers[node_id] = handler

    def partition(self, node_id: str) -> None:
        with self._lock:
            self._partitioned.add(node_id)

    def heal(self, node_id: str) -> None:
        with self._lock:
            self._partitioned.discard(node_id)

    def send(self, from_id: str, to_id: str, msg: dict) -> Optional[dict]:
        with self._lock:
            if from_id in self._partitioned or to_id in self._partitioned:
                return None
            handler = self._handlers.get(to_id)
        if handler is None:
            return None
        try:
            return handler(msg)
        except Exception:
            return None
