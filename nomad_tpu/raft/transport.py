"""Raft + server-RPC transport (reference nomad/raft_rpc.go and
nomad/rpc.go:31,445 — msgpack-RPC over yamux TCP).

The node logic only needs `send(peer, message) -> reply`. Two
implementations:

- InProcTransport: direct dispatch, used by tests and single-process
  multi-server topologies, with a partitionable failure set.
- SocketTransport: length-prefixed wire-codec frames over TCP, one
  listener per server, persistent client connections per peer. Carries
  two frame kinds on the same connection: "raft" (the consensus
  messages) and "call" (server-to-server endpoint forwarding — the
  reference's forwardLeader). Payloads go through structs.wire so raft
  log commands containing domain structs survive the trip.
"""

from __future__ import annotations

import json
import logging
import socket
import socketserver
import struct
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from ..utils.backoff import Backoff

log = logging.getLogger("nomad_tpu.raft")


class InProcTransport:
    """A registry of node handlers; send() is a function call with a
    configurable failure set for partition tests.

    Failure model, consulted in order per message:
    - per-node partitions (symmetric: the node is cut from everyone);
    - directed per-link cuts (partition_link(a, b) drops a->b only —
      the asymmetric failures real networks produce);
    - an optional chaos FaultPlan (chaos/plan.py) deciding
      drop/delay/duplicate/reorder per message.
    """

    def __init__(self):
        self._handlers: Dict[str, Callable[[dict], dict]] = {}
        self._lock = threading.Lock()
        self._partitioned: set = set()  # node ids cut off from everyone
        self._cut_links: set = set()    # directed (src, dst) pairs
        self._timers: set = set()       # outstanding late-delivery timers
        self.fault_plan = None          # chaos.FaultPlan or None

    def register(self, node_id: str, handler: Callable[[dict], dict]) -> None:
        with self._lock:
            self._handlers[node_id] = handler

    def unregister(self, node_id: str) -> None:
        """Crashed process: its handler vanishes (chaos crash path)."""
        with self._lock:
            self._handlers.pop(node_id, None)

    def partition(self, node_id: str) -> None:
        with self._lock:
            self._partitioned.add(node_id)

    def partition_link(self, src: str, dst: str) -> None:
        """Cut src -> dst only; dst -> src still delivers."""
        with self._lock:
            self._cut_links.add((src, dst))

    def heal_link(self, src: str, dst: str) -> None:
        with self._lock:
            self._cut_links.discard((src, dst))

    def heal(self, node_id: Optional[str] = None) -> None:
        """Heal one node's symmetric partition, or — with no argument —
        heal everything: node partitions and directed link cuts."""
        with self._lock:
            if node_id is None:
                self._partitioned.clear()
                self._cut_links.clear()
            else:
                self._partitioned.discard(node_id)

    def set_fault_plan(self, plan) -> None:
        self.fault_plan = plan

    def _deliver_later(self, to_id: str, msg: dict, delay: float) -> None:
        """Late/duplicate delivery: hand the message to whoever holds
        the node id at delivery time (survives crash-restart) and drop
        the reply — the sender already moved on."""
        def fire():
            with self._lock:
                self._timers.discard(t)
                if to_id in self._partitioned:
                    return
                handler = self._handlers.get(to_id)
            if handler is None:
                return
            try:
                handler(msg)
            except Exception:
                log.debug("late-delivered message to %s raised",
                          to_id, exc_info=True)
        t = threading.Timer(delay, fire)
        t.daemon = True
        with self._lock:
            self._timers.add(t)
        t.start()

    def close(self) -> None:
        """Cancel any outstanding late-delivery timers (shutdown path;
        a timer that already fired removed itself)."""
        with self._lock:
            timers = list(self._timers)
            self._timers.clear()
        for t in timers:
            t.cancel()

    def send(self, from_id: str, to_id: str, msg: dict) -> Optional[dict]:
        with self._lock:
            if from_id in self._partitioned or to_id in self._partitioned:
                return None
            if (from_id, to_id) in self._cut_links:
                return None
            handler = self._handlers.get(to_id)
        if handler is None:
            return None
        plan = self.fault_plan
        if plan is not None:
            verdict = plan.decide(from_id, to_id, msg)
            if verdict.drop:
                return None
            if verdict.reorder_after > 0:
                # late delivery out of order with successors; the sender
                # sees message loss (raft tolerates both)
                self._deliver_later(to_id, msg, verdict.reorder_after)
                return None
            if verdict.delay > 0:
                time.sleep(verdict.delay)
            if verdict.duplicate_after > 0:
                self._deliver_later(to_id, msg, verdict.duplicate_after)
        try:
            return handler(msg)
        except Exception:
            log.debug("in-proc handler on %s raised for message from %s",
                      to_id, from_id, exc_info=True)
            return None


# ---------------------------------------------------------------------------
# TCP sockets
# ---------------------------------------------------------------------------


def _encode_frame(payload: dict) -> bytes:
    """Serialize once, outside any connection lock: batched
    append_entries frames are the largest thing on the wire now, and
    encoding them while holding the per-connection lock would stall the
    next frame behind CPU work instead of just the socket."""
    data = json.dumps(payload).encode()
    return struct.pack(">I", len(data)) + data


def _send_frame(sock: socket.socket, payload: dict) -> None:
    sock.sendall(_encode_frame(payload))


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket) -> Optional[dict]:
    head = _recv_exact(sock, 4)
    if head is None:
        return None
    (length,) = struct.unpack(">I", head)
    if length > 256 * 1024 * 1024:
        raise ValueError(f"frame too large: {length}")
    body = _recv_exact(sock, length)
    if body is None:
        return None
    return json.loads(body)


class SocketTransport:
    """TCP transport for one server process.

    bind_addr/peer_addrs are "host:port" strings; peers maps server id ->
    address. Incoming frames dispatch to the registered raft handler or
    the call handler; outgoing sends hold one persistent connection per
    peer and treat any socket error as message loss (raft tolerates it).
    """

    # nomadload ingress bounds: a flooding peer is answered RetryLater
    # instead of queueing unbounded handler threads. Raft/snap frames
    # (consensus liveness = tier 0) and tier-0 forwarded calls are
    # never bounded.
    DEFAULT_MAX_INFLIGHT_PER_PEER = 64
    # pending-accept backlog (listen(2) queue) — beyond it the kernel
    # refuses new connections instead of parking them invisibly
    ACCEPT_BACKLOG = 128

    def __init__(self, node_id: str, bind_addr: str,
                 peer_addrs: Dict[str, str], timeout: float = 5.0,
                 connect_timeout: float = 0.3, retry_cooldown: float = 0.5,
                 raft_timeout: float = 0.5,
                 max_inflight_per_peer: Optional[int] = None):
        self.node_id = node_id
        self.bind_addr = bind_addr
        self.peer_addrs = dict(peer_addrs)
        self.timeout = timeout
        self.max_inflight_per_peer = (
            self.DEFAULT_MAX_INFLIGHT_PER_PEER
            if max_inflight_per_peer is None else max_inflight_per_peer)
        self._inflight: Dict[str, int] = {}   # peer host -> frames in dispatch
        self._inflight_lock = threading.Lock()
        self.dropped_frames = 0
        # Raft ticks send to every peer serially: connecting to a dead
        # peer must fail fast and then back off, or one crashed server
        # stalls heartbeats to the live ones and triggers elections. The
        # same goes for a HUNG peer (SIGSTOP, IO stall): raft frames get
        # their own short recv timeout, and any raft-channel failure puts
        # the peer in the cooldown so subsequent ticks skip it instead of
        # blocking the heartbeat fan-out.
        self.connect_timeout = connect_timeout
        self.retry_cooldown = retry_cooldown
        self.raft_timeout = raft_timeout
        self._raft_handler: Optional[Callable[[dict], dict]] = None
        self._call_handler: Optional[Callable[[str, tuple, dict], object]] = None
        self._conns: Dict[Tuple[str, str], socket.socket] = {}
        self._conn_locks: Dict[Tuple[str, str], threading.Lock] = {}
        self._down_until: Dict[Tuple[str, str], float] = {}
        # per-link escalating reconnect backoff (utils/backoff.py): a
        # peer that stays down is probed ever more slowly up to the cap,
        # and a restarted peer resets to the base on first contact
        self._backoffs: Dict[Tuple[str, str], Backoff] = {}
        self._exhaustion_logged: set = set()
        self._lock = threading.Lock()
        self._server: Optional[socketserver.ThreadingTCPServer] = None
        self.fault_plan = None  # chaos.FaultPlan or None

    # -- registration (transport interface) --

    def register(self, node_id: str, handler: Callable[[dict], dict]) -> None:
        assert node_id == self.node_id, "socket transport serves one node"
        self._raft_handler = handler

    def register_call_handler(
            self, handler: Callable[[str, tuple, dict], object]) -> None:
        """handler(method, args, kwargs) -> result; exceptions propagate
        back to the caller as typed error replies."""
        self._call_handler = handler

    def set_fault_plan(self, plan) -> None:
        """Attach a chaos FaultPlan consulted per outgoing raft frame."""
        self.fault_plan = plan

    # -- server side --

    def start(self) -> "SocketTransport":
        host, port = self._split(self.bind_addr)
        transport = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                peer = self.client_address[0]
                while True:
                    try:
                        frame = _recv_frame(self.request)
                    except Exception:
                        log.debug("rpc connection to %s dropped mid-frame",
                                  transport.node_id, exc_info=True)
                        return
                    if frame is None:
                        return
                    try:
                        reply = transport._dispatch(frame, peer=peer)
                    except Exception as e:  # typed error back to caller
                        reply = {"ok": False, "error": str(e),
                                 "error_type": type(e).__name__,
                                 "leader_id": getattr(e, "leader_id", None)}
                    try:
                        _send_frame(self.request, reply)
                    except Exception:
                        log.debug("rpc reply from %s lost: peer closed "
                                  "the connection", transport.node_id,
                                  exc_info=True)
                        return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True
            # bounded pending-accept backlog (nomadload ingress bounds)
            request_queue_size = SocketTransport.ACCEPT_BACKLOG

        self._server = Server((host, port), Handler)
        t = threading.Thread(target=self._server.serve_forever, daemon=True,
                             name=f"rpc-{self.node_id}")
        t.start()
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
        with self._lock:
            for s in self._conns.values():
                try:
                    s.close()
                except OSError:
                    pass
            self._conns.clear()

    def _dispatch(self, frame: dict, peer: str = "") -> dict:
        from ..structs.wire import wire_decode, wire_encode

        kind = frame.get("t")
        if kind in ("raft", "snap"):
            # consensus traffic is tier 0: never bounded, never shed
            if self._raft_handler is None:
                return {"ok": False, "error": "no raft handler"}
            reply = self._raft_handler(wire_decode(frame["m"]))
            return {"ok": True, "m": wire_encode(reply)}
        if kind == "call":
            if self._call_handler is None:
                return {"ok": False, "error": "no call handler"}
            from ..core import loadctl

            method = frame.get("method", "")
            tier = loadctl.tier_for_method(method)
            if tier > loadctl.TIER_LIVENESS \
                    and not self._frame_slot(peer):
                # per-peer inflight cap tripped: refuse the frame with
                # a typed RetryLater the forwarding server decodes and
                # passes through to its client as 429 — never applies
                # to tier-0 (liveness) calls
                self.dropped_frames += 1
                from ..core.metrics import REGISTRY
                REGISTRY.incr("nomad.transport.dropped_frames")
                err = loadctl.RetryLater(
                    tier, 0.25, reason="transport inflight cap")
                return {"ok": False, "error": str(err),
                        "error_type": "RetryLater", "leader_id": None}
            with self._inflight_lock:
                self._inflight[peer] = self._inflight.get(peer, 0) + 1
            try:
                # the forwarded request's absolute deadline rides the
                # frame; expired work is dropped before dispatch
                with loadctl.bind_deadline(frame.get("dl")), \
                        loadctl.bind_tier(tier):
                    if loadctl.drop_if_expired("transport_dispatch"):
                        raise TimeoutError(
                            "request deadline passed before dispatch")
                    result = self._call_handler(
                        method,
                        tuple(wire_decode(frame.get("args", []))),
                        wire_decode(frame.get("kwargs", {})))
            finally:
                with self._inflight_lock:
                    left = self._inflight.get(peer, 1) - 1
                    if left <= 0:
                        self._inflight.pop(peer, None)
                    else:
                        self._inflight[peer] = left
            return {"ok": True, "result": wire_encode(result)}
        return {"ok": False, "error": f"unknown frame kind {kind!r}"}

    def _frame_slot(self, peer: str) -> bool:
        """True when the peer is under its inflight-frame cap."""
        if self.max_inflight_per_peer <= 0:
            return True
        with self._inflight_lock:
            return self._inflight.get(peer, 0) < self.max_inflight_per_peer

    # -- client side --

    @staticmethod
    def _split(addr: str) -> Tuple[str, int]:
        host, _, port = addr.rpartition(":")
        return host or "127.0.0.1", int(port)

    def _mark_down(self, key: Tuple[str, str]) -> None:
        """Peer unreachable: schedule the next probe on an escalating
        jittered backoff; log once when the backoff saturates (retry
        exhaustion — the peer has been down for many probes)."""
        with self._lock:
            bo = self._backoffs.get(key)
            if bo is None:
                bo = self._backoffs[key] = Backoff(
                    base=self.retry_cooldown, factor=2.0,
                    cap=max(self.retry_cooldown * 8, 2.0), jitter=0.2)
            at_cap = bo.at_cap()
            self._down_until[key] = time.monotonic() + bo.next_delay()
            if at_cap and key not in self._exhaustion_logged:
                self._exhaustion_logged.add(key)
                log.warning(
                    "%s: peer %s (%s channel) unreachable after %d "
                    "attempts; retrying at the capped interval",
                    self.node_id, key[0], key[1], bo.attempt)

    def _mark_up(self, key: Tuple[str, str]) -> None:
        with self._lock:
            self._down_until.pop(key, None)
            bo = self._backoffs.get(key)
            if bo is not None:
                bo.reset()
            if key in self._exhaustion_logged:
                self._exhaustion_logged.discard(key)
                log.info("%s: peer %s (%s channel) reachable again",
                         self.node_id, key[0], key[1])

    def _conn(self, key: Tuple[str, str]) \
            -> Tuple[socket.socket, threading.Lock, bool]:
        """Returns (socket, per-connection lock, was_cached). A cached
        socket may be stale (peer restarted since) — callers sending
        idempotent frames retry once on a fresh connection."""
        with self._lock:
            lock = self._conn_locks.setdefault(key, threading.Lock())
            sock = self._conns.get(key)
            if sock is None and time.monotonic() < self._down_until.get(key, 0):
                raise TransportError(f"{key[0]} in reconnect cooldown")
        if sock is not None:
            return sock, lock, True
        host, port = self._split(self.peer_addrs[key[0]])
        try:
            sock = socket.create_connection((host, port),
                                            timeout=self.connect_timeout)
        except OSError:
            self._mark_down(key)
            raise
        self._mark_up(key)
        sock.settimeout(self.raft_timeout if key[1] == "raft" else self.timeout)
        with self._lock:
            # lost a race? keep the first connection
            existing = self._conns.get(key)
            if existing is not None:
                sock.close()
                return existing, lock, True
            self._conns[key] = sock
        return sock, lock, False

    def _drop(self, key: Tuple[str, str]) -> None:
        with self._lock:
            sock = self._conns.pop(key, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _roundtrip(self, to_id: str, frame: dict) -> Optional[dict]:
        if to_id not in self.peer_addrs:
            return None
        # separate connections per frame kind so a large forwarded call
        # can't stall raft heartbeats behind it (the reference gets this
        # from yamux stream multiplexing)
        key = (to_id, frame["t"])
        # encode before taking the connection lock, and only once even
        # if the stale-connection retry below resends the frame
        wire_frame = _encode_frame(frame)
        for attempt in (0, 1):
            try:
                sock, lock, cached = self._conn(key)
            except Exception:
                log.debug("%s: cannot reach %s", self.node_id, to_id,
                          exc_info=True)
                return None
            try:
                with lock:  # one in-flight request per connection
                    sock.sendall(wire_frame)
                    reply = _recv_frame(sock)
            except Exception:
                self._drop(key)
                if cached and attempt == 0:
                    # a cached connection that dies is the signature of
                    # a RESTARTED peer: raft frames are idempotent, so
                    # retry once on a fresh connection instead of
                    # failing the send permanently
                    continue
                # hung or dead peer: back off so serial raft fan-outs
                # keep heartbeating the healthy peers
                self._mark_down(key)
                return None
            if reply is None:
                self._drop(key)
                if cached and attempt == 0:
                    continue
                self._mark_down(key)
                return None
            return reply
        return None

    def send(self, from_id: str, to_id: str, msg: dict) -> Optional[dict]:
        """Raft message send (transport interface). Snapshot installs get
        their own channel: even chunked frames (SNAPSHOT_CHUNK_BYTES per
        install_snapshot message) are large enough to want the long
        timeout, and the short raft timeout exists precisely so
        heartbeats never wait on a transfer like that."""
        from ..structs.wire import wire_decode, wire_encode

        channel = "snap" if msg.get("kind") == "install_snapshot" else "raft"
        frame = {"t": channel, "m": wire_encode(msg)}
        plan = self.fault_plan
        if plan is not None:
            verdict = plan.decide(self.node_id, to_id, msg)
            if verdict.drop:
                return None
            if verdict.reorder_after > 0:
                # deliver late from a side thread, reply discarded;
                # raft treats the original send as lost
                t = threading.Timer(verdict.reorder_after,
                                    self._roundtrip, (to_id, frame))
                t.daemon = True
                t.start()
                return None
            if verdict.delay > 0:
                time.sleep(verdict.delay)
            if verdict.duplicate_after > 0:
                t = threading.Timer(verdict.duplicate_after,
                                    self._roundtrip, (to_id, frame))
                t.daemon = True
                t.start()
        reply = self._roundtrip(to_id, frame)
        if reply is None or not reply.get("ok"):
            return None
        return wire_decode(reply["m"])

    def call(self, to_id: str, method: str, args: tuple = (),
             kwargs: Optional[dict] = None):
        """Forwarded server call; raises RemoteCallError on typed errors
        and TransportError on connectivity loss. TransportError carries
        maybe_delivered=True when the frame left this host before the
        connection died — the peer may have executed the call, so the
        caller must not blindly retry non-idempotent methods."""
        from ..structs.wire import wire_decode, wire_encode

        if to_id not in self.peer_addrs:
            raise TransportError(f"unknown peer {to_id}")
        frame = {"t": "call", "method": method,
                 "args": wire_encode(list(args)),
                 "kwargs": wire_encode(kwargs or {})}
        from ..core import loadctl

        dl = loadctl.current_deadline()
        if dl is not None:
            frame["dl"] = dl  # absolute deadline rides the wire

        key = (to_id, "call")
        wire_frame = _encode_frame(frame)
        for attempt in (0, 1):
            try:
                sock, lock, _cached = self._conn(key)
            except TransportError:
                raise
            except Exception as e:  # connect failed: definitely not delivered
                raise TransportError(f"cannot reach {to_id}: {e}") from e
            try:
                with lock:
                    try:
                        sock.sendall(wire_frame)
                    except OSError as e:
                        # another thread dropped this shared socket before
                        # we sent a byte (EBADF/ENOTCONN): provably not
                        # delivered, so one fresh-connection retry is safe
                        self._drop(key)
                        import errno

                        if attempt == 0 and e.errno in (errno.EBADF,
                                                        errno.ENOTCONN):
                            continue
                        err = TransportError(
                            f"send to {to_id} failed mid-call: {e}")
                        err.maybe_delivered = True
                        raise err from e
                    reply = _recv_frame(sock)
            except TransportError:
                raise
            except Exception as e:
                self._drop(key)
                err = TransportError(f"connection to {to_id} lost mid-call: {e}")
                err.maybe_delivered = True
                raise err from e
            break
        if reply is None:
            self._drop(key)
            err = TransportError(f"{to_id} closed the connection before replying")
            err.maybe_delivered = True
            raise err
        if not reply.get("ok"):
            raise RemoteCallError(reply.get("error_type", "Exception"),
                                  reply.get("error", ""),
                                  reply.get("leader_id"))
        return wire_decode(reply["result"])


class TransportError(Exception):
    maybe_delivered = False


class RemoteCallError(Exception):
    def __init__(self, error_type: str, message: str, leader_id=None):
        super().__init__(f"{error_type}: {message}")
        self.error_type = error_type
        self.leader_id = leader_id
