"""Durable raft storage: on-disk log, stable term/vote store, and FSM
snapshot files.

Reference: hashicorp/raft's boltdb LogStore/StableStore
(nomad/server.go:1365 setupRaft) and FileSnapshotStore. Here the log is
an append-only JSONL file (commands are wire-encoded, structs/wire.py),
term/vote is an atomically-replaced JSON file, and snapshots are whole
state dumps (state/persist.py) with index/term metadata. Compaction
rewrites the log keeping only entries past the snapshot.

Layout under <dir>/:
    log.jsonl       one entry per line: {"index","term","command"}
    stable.json     {"term": N, "voted_for": id}
    snapshot.json   {"index","term","data"}
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
from typing import List, Optional, Tuple

from ..structs.wire import wire_decode, wire_encode
from ..utils.files import atomic_write_text as _atomic_write
from ..utils.files import check_fault as _check_fault
from .log import Entry

log = logging.getLogger("nomad_tpu.raft")


def snapshot_digest(text: str) -> str:
    """Whole-snapshot content digest for the chunked install protocol:
    the follower only restores once the accumulated bytes hash to what
    the leader announced with the final chunk."""
    return hashlib.sha256(text.encode("utf-8")).hexdigest()


def _load_snapshot_file(path: str) -> Optional[dict]:
    """Read snapshot.json, tolerating a torn/corrupt file: a snapshot
    that doesn't parse is treated as absent (warn + None) — the node
    starts empty and the leader re-installs — never a bricked server.
    The normal save path is atomic (tmp + fsync + rename), so this only
    fires on truly exceptional artifacts (partial copy, bit rot)."""
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            data = json.load(f)
        if not isinstance(data, dict) or "index" not in data:
            raise ValueError("snapshot file missing index")
        return data
    except (ValueError, KeyError, OSError) as e:
        log.warning("%s: unreadable snapshot dropped (%s); "
                    "treating as absent", path, e)
        return None


class StableStore:
    """current_term + voted_for survive restarts (Raft's persistent
    per-server state; losing it can double-vote in one term)."""

    def __init__(self, dir_path: str):
        self._path = os.path.join(dir_path, "stable.json")
        self.term = 0
        self.voted_for: Optional[str] = None
        if os.path.exists(self._path):
            with open(self._path) as f:
                data = json.load(f)
            self.term = int(data.get("term", 0))
            self.voted_for = data.get("voted_for")

    def save(self, term: int, voted_for: Optional[str]) -> None:
        # disk first: if the write fails (ENOSPC, injected fault), the
        # in-memory view must not claim a persistence that never happened
        _atomic_write(self._path,
                      json.dumps({"term": term, "voted_for": voted_for}))
        self.term = term
        self.voted_for = voted_for


class SnapshotStore:
    """snapshot.json plus a chunk-transfer staging file.

    `last_index` tracks the index of the snapshot currently on disk
    (kept current by save/load) so `save(..., only_if_newer=True)` can
    reject a stale write without parsing the file — the off-lock
    snapshot thread uses it to lose the race against a concurrent
    install_snapshot cleanly."""

    def __init__(self, dir_path: str):
        self._path = os.path.join(dir_path, "snapshot.json")
        self._partial = self._path + ".partial"
        self._lock = threading.Lock()
        self.last_index = -1

    def save(self, index: int, term: int, data: dict,
             servers: Optional[dict] = None,
             only_if_newer: bool = False) -> bool:
        payload = {"index": index, "term": term, "data": data}
        if servers:
            payload["servers"] = servers
        return self._save_text(index, json.dumps(payload), only_if_newer)

    def save_raw(self, index: int, term: int, data_text: str,
                 servers: Optional[dict] = None,
                 only_if_newer: bool = False) -> bool:
        """Save with the FSM dump already serialized (`data_text` is the
        JSON text of the "data" value) — the chunked install path splices
        the accumulated transfer bytes straight in instead of
        parse-then-reserialize at C2M sizes."""
        head = {"index": index, "term": term}
        if servers:
            head["servers"] = servers
        text = json.dumps(head)[:-1] + ', "data": ' + data_text + "}"
        return self._save_text(index, text, only_if_newer)

    def _save_text(self, index: int, text: str,
                   only_if_newer: bool) -> bool:
        with self._lock:
            if only_if_newer and index <= self.last_index:
                log.info("%s: skipping stale snapshot save at index %d "
                         "(disk already at %d)",
                         self._path, index, self.last_index)
                return False
            _atomic_write(self._path, text)
            self.last_index = index
            return True

    def load(self) -> Optional[dict]:
        data = _load_snapshot_file(self._path)
        if data is not None:
            with self._lock:
                self.last_index = max(self.last_index, int(data["index"]))
        return data

    def sink(self) -> "FileSnapshotSink":
        """A staging sink for an incoming chunked transfer. Writes land
        in snapshot.json.partial; the real snapshot file is untouched
        until the caller verifies the digest and calls save_raw."""
        return FileSnapshotSink(self._partial)


class FileSnapshotSink:
    """Accumulates a chunked snapshot transfer in a temp file next to
    snapshot.json. Crash/disconnect mid-transfer leaves only this file
    behind — the previous snapshot stays loadable. Writes go through
    the `check_fault("snap_chunk")` chokepoint so chaos scenarios can
    tear the transfer at any offset."""

    def __init__(self, path: str):
        self._path = path
        self._fh = None
        self.offset = 0

    def write(self, data: str) -> None:
        _check_fault("snap_chunk", self._path)
        if self._fh is None:
            self._fh = open(self._path, "w")
        self._fh.write(data)
        self._fh.flush()
        self.offset += len(data)

    def read_all(self) -> str:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        if not os.path.exists(self._path):
            return ""
        with open(self._path) as f:
            return f.read()

    def discard(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        try:
            os.unlink(self._path)
        except OSError:
            pass
        self.offset = 0


class MemorySnapshotSink:
    """Chunk accumulator for nodes running without durable storage
    (in-proc tests): same surface as FileSnapshotSink."""

    def __init__(self):
        self._buf: List[str] = []
        self.offset = 0

    def write(self, data: str) -> None:
        self._buf.append(data)
        self.offset += len(data)

    def read_all(self) -> str:
        return "".join(self._buf)

    def discard(self) -> None:
        self._buf = []
        self.offset = 0


class DurableLog:
    """RaftLog-compatible append-only disk log with a compaction base.

    Indexes are 1-based and global; after compaction the log physically
    starts at base_index+1 (base_index/base_term describe the snapshot
    boundary, like hashicorp/raft's firstIndex after log truncation).
    """

    def __init__(self, dir_path: str, fsync: bool = True):
        self._dir = dir_path
        self._path = os.path.join(dir_path, "log.jsonl")
        self._fsync = fsync
        self._lock = threading.Lock()
        self.base_index = 0
        self.base_term = 0
        self._entries: List[Entry] = []  # entries base_index+1 .. last
        self._fh = None
        self._load()

    # -- persistence internals --

    def _load(self) -> None:
        meta = _load_snapshot_file(os.path.join(self._dir, "snapshot.json"))
        if meta is not None:
            self.base_index = int(meta.get("index", 0))
            self.base_term = int(meta.get("term", 0))
        if os.path.exists(self._path):
            good_offset = 0
            torn = False
            with open(self._path, "rb") as f:
                for raw in f:
                    line = raw.decode("utf-8", errors="replace").strip()
                    if line:
                        try:
                            rec = json.loads(line)
                            e = Entry(index=int(rec["index"]),
                                      term=int(rec["term"]),
                                      command=tuple(
                                          wire_decode(rec["command"])))
                        except (ValueError, KeyError, TypeError):
                            # torn tail write (crash mid-append) — or a
                            # JSON-shaped fragment missing fields: drop
                            # it and everything after; never brick the
                            # server on restart
                            torn = True
                            break
                        if e.index > self.base_index:
                            # conflict-truncated entries may linger
                            # physically; keep the last write per index
                            pos = e.index - self.base_index - 1
                            if pos < len(self._entries):
                                del self._entries[pos:]
                            elif pos > len(self._entries):
                                good_offset += len(raw)
                                continue  # stale pre-compaction line
                            self._entries.append(e)
                    good_offset += len(raw)
            if torn:
                last_idx = (self._entries[-1].index if self._entries
                            else self.base_index)
                dropped = os.path.getsize(self._path) - good_offset
                log.warning(
                    "%s: torn tail (%d byte(s) past entry %d) dropped; "
                    "truncating to the last good entry",
                    self._path, dropped, last_idx)
                # truncate the garbage so the next append starts clean
                with open(self._path, "r+b") as f:
                    f.truncate(good_offset)
        self._fh = open(self._path, "a")

    def _write(self, entries: List[Entry]) -> None:
        _check_fault("log_append", self._path)
        for e in entries:
            self._fh.write(json.dumps({
                "index": e.index, "term": e.term,
                "command": wire_encode(list(e.command))}) + "\n")
        self._fh.flush()
        if self._fsync:
            os.fsync(self._fh.fileno())

    def _rewrite(self) -> None:
        """Rewrite the whole file from the logical view (truncation or
        compaction — both rare)."""
        _check_fault("log_rewrite", self._path)
        self._fh.close()
        tmp = self._path + ".tmp"
        try:
            with open(tmp, "w") as f:
                for e in self._entries:
                    f.write(json.dumps({
                        "index": e.index, "term": e.term,
                        "command": wire_encode(list(e.command))}) + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._path)
        finally:
            # even a failed rewrite (disk fault) leaves the old file in
            # place atomically; the append handle must come back either
            # way or every later write dies on a closed fh
            self._fh = open(self._path, "a")

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    # -- RaftLog interface --

    def last(self) -> Tuple[int, int]:
        with self._lock:
            if not self._entries:
                return self.base_index, self.base_term
            e = self._entries[-1]
            return e.index, e.term

    def first_index(self) -> int:
        """Lowest index physically present (0 = log empty)."""
        with self._lock:
            return self.base_index + 1 if self._entries else 0

    def term_at(self, index: int) -> int:
        if index == 0:
            return 0
        with self._lock:
            if index == self.base_index:
                return self.base_term
            pos = index - self.base_index - 1
            if pos < 0 or pos >= len(self._entries):
                return -1
            return self._entries[pos].term

    def get(self, index: int) -> Optional[Entry]:
        with self._lock:
            pos = index - self.base_index - 1
            if 0 <= pos < len(self._entries):
                return self._entries[pos]
            return None

    def slice_from(self, index: int, limit: int = 64) -> List[Entry]:
        with self._lock:
            pos = max(0, index - self.base_index - 1)
            return list(self._entries[pos: pos + limit])

    def append(self, term: int, command: tuple) -> Entry:
        with self._lock:
            last = (self._entries[-1].index if self._entries
                    else self.base_index)
            e = Entry(index=last + 1, term=term, command=command)
            self._entries.append(e)
            try:
                self._write([e])
            except OSError:
                # disk fault (ENOSPC/EIO): roll the in-memory entry back
                # so memory never claims an entry the disk lost — a
                # crash-restart would otherwise drop an acked write
                del self._entries[-1]
                raise
            return e

    def append_batch(self, term: int, commands: List[tuple],
                     prev: Optional[Tuple[int, int]] = None
                     ) -> Optional[List[Entry]]:
        """Group commit: append a whole batch of commands with ONE
        buffered write and ONE fsync — the amortization the leader's
        log-writer thread lives on.

        When ``prev`` is given the append is conditional on the tail
        still being exactly ``(last_index, last_term)``; a concurrent
        append (config entry, new-leader noop, post-step-down
        truncation) fails the compare-and-swap and returns None, so the
        caller re-reads the tail instead of writing onto a diverged
        log. Entries become visible (and replicable) only after the
        fsync returns: memory never claims what disk might lose, and a
        disk fault rolls the whole batch back — the same atomicity
        contract as append()."""
        with self._lock:
            if not self._entries:
                tail = (self.base_index, self.base_term)
            else:
                e = self._entries[-1]
                tail = (e.index, e.term)
            if prev is not None and tail != tuple(prev):
                return None
            batch = [Entry(index=tail[0] + 1 + i, term=term, command=c)
                     for i, c in enumerate(commands)]
            before = len(self._entries)
            self._entries.extend(batch)
            try:
                self._write(batch)
            except OSError:
                # one fault fails the whole batch: every entry rolls
                # back together, so there is never a gap where a prefix
                # is durable but memory claims the full batch
                del self._entries[before:]
                raise
            return batch

    def append_entries(self, prev_index: int, entries: List[Entry]) -> bool:
        with self._lock:
            before_len = len(self._entries)
            appended: List[Entry] = []
            truncated = False
            for e in entries:
                if e.index <= self.base_index:
                    continue  # snapshot already covers it
                pos = e.index - self.base_index - 1
                if pos < len(self._entries):
                    if self._entries[pos].term != e.term:
                        del self._entries[pos:]
                        self._entries.append(e)
                        truncated = True
                        appended = [e]
                    # else: already have it
                else:
                    self._entries.append(e)
                    appended.append(e)
            try:
                if truncated:
                    self._rewrite()
                elif appended:
                    self._write(appended)
            except OSError:
                if not truncated:
                    # plain-append fault: shed the entries the disk
                    # never saw (the follower will nack and be retried)
                    del self._entries[before_len:]
                raise
            return truncated

    def length(self) -> int:
        with self._lock:
            return len(self._entries)

    # -- compaction --

    def compact(self, upto_index: int, upto_term: int) -> None:
        """Drop entries <= upto_index (now covered by a snapshot)."""
        with self._lock:
            if self._fh is None:
                # closed mid-race by a crash/stop (the async snapshot
                # worker outlives the node lock); the snapshot is saved,
                # compaction just waits for the next round
                return
            keep = upto_index - self.base_index
            if keep <= 0:
                return
            del self._entries[:keep]
            self.base_index = upto_index
            self.base_term = upto_term
            self._rewrite()

    def reset_to(self, index: int, term: int) -> None:
        """Install-snapshot on a follower: discard everything, restart
        the log at the snapshot boundary."""
        with self._lock:
            if self._fh is None:
                return
            self._entries.clear()
            self.base_index = index
            self.base_term = term
            self._rewrite()
