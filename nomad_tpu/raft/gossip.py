"""SWIM-style UDP gossip membership (reference nomad/serf.go — the
Serf LAN/WAN gossip that discovers servers, health-checks them, and
feeds autopilot + region federation).

A GossipAgent per server: JSON datagrams over UDP carrying the full
member map (anti-entropy full-state merge — exact at the handful-of-
servers scale a control plane runs at, where SWIM's O(1) piggyback
dissemination buys nothing). Protocol:

    ping: {"t": "ping", "from": id, "m": {member map}}
    ack:  {"t": "ack",  "from": id, "m": {member map}}

Liveness: every `interval` the agent probes one random live member; a
probe with no ack within `ack_timeout` marks the member SUSPECT, and a
suspect past `suspect_timeout` is DEAD (no indirect probes — at control
plane scale every member probes every other within a few rounds, which
is the redundancy indirect probing exists to approximate). Merge rules
are standard SWIM: higher incarnation wins; at equal incarnation
dead > suspect > alive; a member refutes suspicion about ITSELF by
bumping its incarnation. Receiving any datagram from a member is direct
proof of life.

Members carry metadata (raft RPC address, region, HTTP address) so the
consumers need no second lookup:
- ReplicatedServer auto-joins gossip-discovered servers into the raft
  configuration and reaps gossip-dead ones (the reference's
  serverHealth-driven autopilot, nomad/server.go:1602);
- foreign-region members keep the federation region registry fresh
  (reference WAN serf feeding multi-region forwarding).
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import json
import random
import socket
import threading
import time
from typing import Callable, Dict, Optional

ALIVE = "alive"
SUSPECT = "suspect"
DEAD = "dead"

_PRECEDENCE = {ALIVE: 0, SUSPECT: 1, DEAD: 2}


class GossipAgent:
    # a DEAD tombstone this old is dropped from the member map (and a
    # remote map's DEAD entry for an unknown member is never adopted),
    # so full-state datagrams don't grow forever across server churn —
    # the reason Serf reaps tombstones
    DEAD_REAP_S = 60.0

    def __init__(self, node_id: str, bind: str = "127.0.0.1:0", *,
                 meta: Optional[dict] = None,
                 interval: float = 0.5,
                 ack_timeout: float = 0.4,
                 suspect_timeout: float = 2.0,
                 key: Optional[bytes] = None,
                 on_change: Optional[Callable[[str, dict], None]] = None,
                 logger=None):
        self.id = node_id
        self.interval = interval
        self.ack_timeout = ack_timeout
        self.suspect_timeout = suspect_timeout
        # shared-secret datagram authentication (reference: Serf's
        # encrypted gossip): with a key set, unsigned or mis-signed
        # datagrams are DROPPED — otherwise anyone who can reach the
        # UDP port could inject members into the raft voter set
        self._key = key
        self.on_change = on_change
        self.logger = logger
        host, port = bind.rsplit(":", 1)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind((host, int(port)))
        self._sock.settimeout(0.2)
        self.bind_addr = "%s:%d" % self._sock.getsockname()
        self._lock = threading.Lock()
        self.members: Dict[str, dict] = {
            node_id: {"gossip": self.bind_addr, "inc": 1, "status": ALIVE,
                      "meta": dict(meta or {})}}
        # member id -> deadline of the outstanding probe
        self._pending: Dict[str, float] = {}
        # suspect since (local clock)
        self._suspect_at: Dict[str, float] = {}
        self._stop = threading.Event()
        self._threads = []

    # -- lifecycle --

    def start(self) -> "GossipAgent":
        for name, fn in (("gossip-rx", self._run_rx),
                         ("gossip-probe", self._run_probe)):
            t = threading.Thread(target=fn, daemon=True,
                                 name=f"{name}-{self.id}")
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2.0)
        try:
            self._sock.close()
        except OSError:
            pass

    def join(self, seed_addr: str) -> None:
        """Introduce ourselves to one seed; the merge does the rest."""
        self._send(seed_addr, {"t": "ping", "from": self.id,
                               "m": self._snapshot()})

    # -- wire --

    def _send(self, addr: str, msg: dict) -> None:
        host, port = addr.rsplit(":", 1)
        payload = json.dumps(msg, sort_keys=True)
        if self._key is not None:
            sig = _hmac.new(self._key, payload.encode(),
                            hashlib.sha256).hexdigest()
            payload = json.dumps({"p": payload, "sig": sig})
        try:
            self._sock.sendto(payload.encode(), (host, int(port)))
        except OSError:
            pass

    def _snapshot(self) -> dict:
        with self._lock:
            return {mid: {k: v for k, v in m.items()}
                    for mid, m in self.members.items()}

    def _run_rx(self) -> None:
        while not self._stop.is_set():
            try:
                data, src = self._sock.recvfrom(256 * 1024)
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                msg = json.loads(data)
            except ValueError:
                continue
            if self._key is not None:
                payload = msg.get("p")
                sig = msg.get("sig", "")
                if not isinstance(payload, str):
                    continue  # unsigned datagram with a key configured
                want = _hmac.new(self._key, payload.encode(),
                                 hashlib.sha256).hexdigest()
                if not _hmac.compare_digest(want, sig):
                    continue
                try:
                    msg = json.loads(payload)
                except ValueError:
                    continue
            elif "p" in msg and "sig" in msg:
                continue  # signed traffic from a keyed peer: can't verify
            sender = msg.get("from", "")
            self._merge(msg.get("m") or {})
            if sender and sender != self.id:
                # direct proof of life beats any gossiped suspicion
                self._evidence_alive(sender)
                with self._lock:
                    self._pending.pop(sender, None)
            if msg.get("t") == "ping":
                peer = self.members.get(sender)
                addr = (peer or {}).get("gossip", "")
                if addr:
                    self._send(addr, {"t": "ack", "from": self.id,
                                      "m": self._snapshot()})

    def _run_probe(self) -> None:
        while not self._stop.wait(self.interval):
            now = time.time()
            with self._lock:
                # outstanding probe expired -> suspect
                for mid, deadline in list(self._pending.items()):
                    if now >= deadline:
                        del self._pending[mid]
                        self._set_status_locked(mid, SUSPECT)
                # old tombstones fall out of the map entirely
                for mid, m in list(self.members.items()):
                    if (m["status"] == DEAD and mid != self.id
                            and now - m.get("dead_at", now)
                            >= self.DEAD_REAP_S):
                        del self.members[mid]
                        self._pending.pop(mid, None)
                        self._suspect_at.pop(mid, None)
                # suspicion expired -> dead
                for mid, since in list(self._suspect_at.items()):
                    m = self.members.get(mid)
                    if m is None or m["status"] != SUSPECT:
                        del self._suspect_at[mid]
                    elif now - since >= self.suspect_timeout:
                        del self._suspect_at[mid]
                        self._set_status_locked(mid, DEAD)
                candidates = [
                    (mid, m["gossip"]) for mid, m in self.members.items()
                    if mid != self.id and m["status"] != DEAD
                    and m.get("gossip") and mid not in self._pending]
            if not candidates:
                continue
            mid, addr = random.choice(candidates)
            with self._lock:
                self._pending[mid] = now + self.ack_timeout
            self._send(addr, {"t": "ping", "from": self.id,
                              "m": self._snapshot()})

    # -- membership state machine --

    def _evidence_alive(self, mid: str) -> None:
        with self._lock:
            m = self.members.get(mid)
            if m is None:
                return
            if m["status"] != ALIVE:
                # direct contact refutes gossiped suspicion/death at the
                # member's current incarnation
                m["inc"] += 1
                self._set_status_locked(mid, ALIVE)

    def _set_status_locked(self, mid: str, status: str) -> None:
        m = self.members.get(mid)
        if m is None or m["status"] == status:
            return
        if m["status"] == DEAD and status == SUSPECT:
            # a stale probe expiring must not resurrect a corpse into
            # the suspect/dead flip-flop (only direct contact or a
            # higher incarnation revives); drop the stale probe instead
            self._pending.pop(mid, None)
            return
        m["status"] = status
        if status == DEAD:
            self._pending.pop(mid, None)
        if status == SUSPECT:
            self._suspect_at[mid] = time.time()
        else:
            self._suspect_at.pop(mid, None)
        if status == DEAD:
            m["dead_at"] = time.time()
        self._notify(mid, m)

    def _notify(self, mid: str, m: dict) -> None:
        if self.on_change is not None:
            try:
                self.on_change(mid, dict(m))
            except Exception:
                if self.logger:
                    self.logger.exception("gossip on_change failed")

    def _merge(self, remote: dict) -> None:
        changed = []
        with self._lock:
            for mid, rm in remote.items():
                if not isinstance(rm, dict):
                    continue
                r_inc = int(rm.get("inc", 0))
                r_status = rm.get("status", ALIVE)
                if mid == self.id:
                    # refute rumors of our own demise with a higher
                    # incarnation (SWIM refutation)
                    me = self.members[self.id]
                    if r_status != ALIVE and r_inc >= me["inc"]:
                        me["inc"] = r_inc + 1
                    continue
                mine = self.members.get(mid)
                if mine is None:
                    if r_status == DEAD:
                        continue  # never adopt a tombstone we reaped
                    self.members[mid] = {
                        "gossip": rm.get("gossip", ""),
                        "inc": r_inc, "status": r_status,
                        "meta": dict(rm.get("meta") or {})}
                    if r_status == SUSPECT:
                        self._suspect_at[mid] = time.time()
                    changed.append(mid)
                    continue
                if r_inc > mine["inc"] or (
                        r_inc == mine["inc"]
                        and _PRECEDENCE[r_status]
                        > _PRECEDENCE[mine["status"]]):
                    before = mine["status"]
                    mine["inc"] = r_inc
                    mine["status"] = r_status
                    if r_status == DEAD and "dead_at" not in mine:
                        mine["dead_at"] = time.time()
                    if rm.get("gossip"):
                        mine["gossip"] = rm["gossip"]
                    if rm.get("meta"):
                        mine["meta"] = dict(rm["meta"])
                    if r_status == SUSPECT:
                        self._suspect_at.setdefault(mid, time.time())
                    else:
                        self._suspect_at.pop(mid, None)
                    if before != r_status:
                        changed.append(mid)
            snapshot = {mid: dict(self.members[mid]) for mid in changed}
        for mid in changed:
            self._notify(mid, snapshot[mid])

    # -- read surface --

    def snapshot(self) -> Dict[str, dict]:
        """Locked copy of the full member map."""
        return self._snapshot()

    def alive_members(self) -> Dict[str, dict]:
        with self._lock:
            return {mid: dict(m) for mid, m in self.members.items()
                    if m["status"] == ALIVE}

    def member(self, mid: str) -> Optional[dict]:
        with self._lock:
            m = self.members.get(mid)
            return dict(m) if m else None
