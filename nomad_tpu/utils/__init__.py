"""Cross-cutting helpers (reference helper/ — 40 packages; only what we need)."""

from .ids import generate_secret_uuid, generate_uuid, generate_uuids, short_id  # noqa: F401
