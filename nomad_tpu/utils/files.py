"""Atomic file persistence shared by the raft stores and the client
state DB (reference helper/ file utilities)."""

from __future__ import annotations

import os


def atomic_write_text(path: str, payload: str) -> None:
    """Write-temp + fsync + rename so readers see the old or the new
    file, never a torn one."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
