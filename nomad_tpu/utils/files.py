"""Atomic file persistence shared by the raft stores and the client
state DB (reference helper/ file utilities)."""

from __future__ import annotations

import os
from typing import Callable, Optional

# chaos fs fault shim (chaos/fsfaults.py): a no-op until a scenario
# installs a hook; durable-layer writes call check_fault before disk IO
_fault_hook: Optional[Callable[[str, str], None]] = None


def set_fault_hook(hook: Optional[Callable[[str, str], None]]) -> None:
    global _fault_hook
    _fault_hook = hook


def check_fault(op: str, path: str) -> None:
    hook = _fault_hook
    if hook is not None:
        hook(op, path)


def atomic_write_text(path: str, payload: str) -> None:
    """Write-temp + fsync + rename so readers see the old or the new
    file, never a torn one."""
    check_fault("atomic_write_text", path)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
