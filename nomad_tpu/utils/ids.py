"""UUID helpers (reference helper/uuid).

`uuid.uuid4()` costs an os.urandom syscall per id; at bulk-placement
scale (2M allocations) id minting is a measurable slice of the commit
path. A process-local PRNG seeded once from os.urandom gives the same
128 random bits per id (collision resistance is what matters here — ids
are object NAMES) at ~6x less cost. getrandbits is a single C call, so
concurrent scheduler workers can't interleave mid-update under the GIL.

The fast stream is observable (alloc/eval ids are public API output) and
Mersenne Twister state is recoverable from its outputs, so anything that
acts as a bearer credential MUST use generate_secret_uuid() instead —
same format, CSPRNG-backed.
"""

import os
import random
import secrets

_rng = random.Random(int.from_bytes(os.urandom(16), "big"))


def _format_uuid(h: str) -> str:
    return f"{h[:8]}-{h[8:12]}-{h[12:16]}-{h[16:20]}-{h[20:]}"


def generate_uuid() -> str:
    """Fast non-cryptographic uuid for object names (allocs, evals, ...)."""
    return _format_uuid(f"{_rng.getrandbits(128):032x}")


def generate_secret_uuid() -> str:
    """CSPRNG uuid for bearer credentials (ACL secret_ids, ack tokens)."""
    return _format_uuid(secrets.token_hex(16))


def generate_uuids(n: int) -> list:
    """Batch mint: one urandom syscall + hexlify for n ids (~40% cheaper
    per id than the PRNG path at bulk-placement scale, and CSPRNG-grade
    as a bonus)."""
    h = os.urandom(16 * n).hex()
    return [_format_uuid(h[32 * i:32 * i + 32]) for i in range(n)]


def short_id(full: str) -> str:
    return full.split("-")[0]
