"""UUID helpers (reference helper/uuid).

`uuid.uuid4()` costs an os.urandom syscall per id; at bulk-placement
scale (2M allocations) id minting is a measurable slice of the commit
path. A process-local PRNG seeded once from os.urandom gives the same
128 random bits per id (collision resistance is what matters here — ids
are object names, not secrets) at ~6x less cost. getrandbits is a single
C call, so concurrent scheduler workers can't interleave mid-update
under the GIL.
"""

import os
import random

_rng = random.Random(int.from_bytes(os.urandom(16), "big"))


def generate_uuid() -> str:
    h = f"{_rng.getrandbits(128):032x}"
    return f"{h[:8]}-{h[8:12]}-{h[12:16]}-{h[16:20]}-{h[20:]}"


def short_id(full: str) -> str:
    return full.split("-")[0]
