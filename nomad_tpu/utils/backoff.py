"""Jittered exponential backoff with a deadline (reference helper/backoff
and client/servers retry idioms).

One policy object for every retry loop in the tree — client
registration/heartbeat, leader forwarding (raft/cluster.py _forward),
socket-transport peer reconnect, gossip seed join — replacing the
divergent ad-hoc `while time.time() < deadline: ... sleep(k)` loops.

Two pieces:

- Backoff: a stateful delay sequence `min(cap, base * factor**n)` with
  multiplicative jitter. Give it a seeded `random.Random` for
  reproducible delays (the chaos harness does).
- Retryer: iterate attempts until a deadline or stop event:

      for attempt in Retryer(deadline_s=5.0, base=0.05):
          try:
              return op()
          except TransientError:
              continue  # Retryer sleeps the backoff delay
      raise  # loop exhausted: no attempt succeeded

  The first attempt runs immediately; iteration ends when the next
  sleep would cross the deadline (so a 5 s Retryer never sleeps past
  t+5 s) or when `stop` is set. `Retryer.call(fn)` wraps the common
  case and re-raises the last error on exhaustion.

- RetryBudget: the SRE retry-budget (nomadload, ROBUSTNESS.md
  "Overload envelope"): each first-try request deposits `ratio`
  tokens, each retry withdraws one, so retries stay <= ~ratio of
  request volume no matter how many callers share the budget. When an
  overloaded server starts answering RetryLater/429, an exhausted
  budget makes clients fail fast instead of amplifying the rejection
  storm with synchronized retry waves. A Retryer given `budget=`
  checks it before every retry (never before the first attempt).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Iterator, Optional, Tuple, Type


class Backoff:
    """Exponential delay sequence with jitter; not thread-safe (give
    each retry loop / peer its own instance)."""

    def __init__(self, base: float = 0.05, factor: float = 2.0,
                 cap: float = 5.0, jitter: float = 0.1,
                 rng: Optional[random.Random] = None):
        self.base = base
        self.factor = factor
        self.cap = cap
        self.jitter = jitter
        self._rng = rng if rng is not None else random
        self._attempt = 0

    @property
    def attempt(self) -> int:
        return self._attempt

    def next_delay(self) -> float:
        """The delay before the next attempt; advances the sequence."""
        raw = min(self.cap, self.base * (self.factor ** self._attempt))
        self._attempt += 1
        if self.jitter <= 0:
            return raw
        # full +/- jitter fraction, never negative
        spread = raw * self.jitter
        return max(0.0, raw + self._rng.uniform(-spread, spread))

    def peek(self) -> float:
        """The un-jittered delay the next next_delay() is based on."""
        return min(self.cap, self.base * (self.factor ** self._attempt))

    def at_cap(self) -> bool:
        """True once the un-jittered delay has saturated at `cap`."""
        return self.base * (self.factor ** self._attempt) >= self.cap

    def reset(self) -> None:
        self._attempt = 0


class RetryBudget:
    """Shared retry budget (retries <= ~``ratio`` of requests, SRE
    style). Thread-safe: one instance is shared by every request a
    client token issues.

    Token bucket over *request volume* rather than time: record_request
    deposits ``ratio`` tokens (plus a ``min_rate``/s trickle so an idle
    client can always retry occasionally), spend_retry withdraws 1.0.
    The balance is capped so a long quiet period cannot bank an
    unbounded retry burst."""

    def __init__(self, ratio: float = 0.1, min_rate: float = 1.0,
                 cap: float = 50.0,
                 clock: Callable[[], float] = time.monotonic):
        self.ratio = ratio
        self.min_rate = min_rate
        self.cap = cap
        self._clock = clock
        self._lock = threading.Lock()
        self._tokens = cap
        self._stamp = clock()
        self.stats = {"requests": 0, "retries": 0, "denied": 0}

    def _refill_locked(self, now: float) -> None:
        self._tokens = min(self.cap, self._tokens
                           + (now - self._stamp) * self.min_rate)
        self._stamp = now

    def record_request(self) -> None:
        """Count one first-try request (deposits ``ratio`` tokens)."""
        with self._lock:
            self._refill_locked(self._clock())
            self._tokens = min(self.cap, self._tokens + self.ratio)
            self.stats["requests"] += 1

    def spend_retry(self) -> bool:
        """True (and spends a token) when a retry is inside budget."""
        with self._lock:
            self._refill_locked(self._clock())
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                self.stats["retries"] += 1
                return True
            self.stats["denied"] += 1
            return False

    def balance(self) -> float:
        with self._lock:
            self._refill_locked(self._clock())
            return self._tokens


class Retryer:
    """Deadline-bounded attempt iterator (see module docstring)."""

    def __init__(self, deadline_s: Optional[float], base: float = 0.05,
                 factor: float = 2.0, cap: float = 5.0, jitter: float = 0.1,
                 stop: Optional[threading.Event] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic,
                 rng: Optional[random.Random] = None,
                 budget: Optional[RetryBudget] = None):
        self.deadline_s = deadline_s
        self._backoff = Backoff(base=base, factor=factor, cap=cap,
                                jitter=jitter, rng=rng)
        self._stop = stop
        self._sleep = sleep
        self._clock = clock
        self._budget = budget

    def __iter__(self) -> Iterator[int]:
        start = self._clock()
        attempt = 0
        if self._budget is not None:
            self._budget.record_request()
        while True:
            if self._stop is not None and self._stop.is_set():
                return
            yield attempt
            attempt += 1
            delay = self._backoff.next_delay()
            if self.deadline_s is not None:
                remaining = self.deadline_s - (self._clock() - start)
                if remaining <= 0:
                    return
                delay = min(delay, remaining)
            if self._budget is not None and not self._budget.spend_retry():
                # budget exhausted: fail fast — under a rejection storm
                # every client retrying on schedule IS the storm
                return
            if self._stop is not None:
                # an Event wait doubles as an interruptible sleep
                if self._stop.wait(delay):
                    return
            else:
                self._sleep(delay)

    def call(self, fn: Callable[[], object],
             retry_on: Tuple[Type[BaseException], ...] = (Exception,)):
        """Run fn until it returns, retrying `retry_on`; re-raises the
        last error once the deadline/stop exhausts the attempts."""
        last: Optional[BaseException] = None
        for _ in self:
            try:
                return fn()
            except retry_on as e:
                last = e
        if last is not None:
            raise last
        raise TimeoutError("retry loop stopped before the first attempt")
