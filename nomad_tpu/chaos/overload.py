"""nomadload open-loop arrival generator (chaos `overload` family +
bench.py overload_goodput).

The defining property of an overload test is that the offered load
does NOT let up when the server slows down: a closed-loop client (next
request after the previous reply) self-throttles in lockstep with the
victim and measures a collapse as "slightly higher latency". This
generator precomputes a seeded Poisson arrival schedule and fires each
request at its scheduled time regardless of how the previous one
fared — requests that find the server slow pile up exactly as a
production rejection storm would, and coordinated omission never
flatters the latency numbers (the schedule, not the replies, decides
when work arrives).

Outcome classification: a ``loadctl.RetryLater`` (or any exception
carrying ``status == 429``) counts as *shed* — the overload plane
doing its job; anything else raised counts as an *error*; a return
counts as *ok* with its service latency recorded.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, List, Optional


def _percentile(xs: List[float], q: float) -> float:
    if not xs:
        return 0.0
    ys = sorted(xs)
    i = min(len(ys) - 1, max(0, int(q * (len(ys) - 1) + 0.5)))
    return ys[i]


def arrival_schedule(rate: float, duration: float,
                     seed: int = 0) -> List[float]:
    """Seeded Poisson arrival offsets (seconds from start) covering
    ``duration`` at ``rate`` requests/s."""
    rng = random.Random(seed)
    out: List[float] = []
    t = 0.0
    while True:
        t += rng.expovariate(rate)
        if t >= duration:
            return out
        out.append(t)


def run_open_loop(submit: Callable[[int], object], rate: float,
                  duration: float, seed: int = 0, workers: int = 8,
                  clock: Callable[[], float] = time.monotonic,
                  sleep: Callable[[float], None] = time.sleep,
                  stop: Optional[threading.Event] = None) -> Dict:
    """Drive ``submit(i)`` on the seeded schedule from a worker pool.

    Workers claim arrivals in schedule order; an arrival whose time
    already passed (every worker busy — the server IS overloaded)
    fires immediately with the backlog intact. Returns aggregate
    counters plus service-latency percentiles over the *ok* requests.
    """
    sched = arrival_schedule(rate, duration, seed=seed)
    lock = threading.Lock()
    state = {"next": 0}
    res = {"sent": 0, "ok": 0, "shed": 0, "errors": 0}
    latencies: List[float] = []
    error_samples: List[str] = []
    start = clock()

    def worker():
        from ..core.loadctl import RetryLater
        while True:
            if stop is not None and stop.is_set():
                return
            with lock:
                i = state["next"]
                if i >= len(sched):
                    return
                state["next"] = i + 1
            wait = sched[i] - (clock() - start)
            if wait > 0:
                sleep(wait)
            t0 = clock()
            try:
                submit(i)
            except RetryLater:
                with lock:
                    res["sent"] += 1
                    res["shed"] += 1
                continue
            except Exception as e:  # noqa: BLE001 — classify, don't die
                with lock:
                    res["sent"] += 1
                    if getattr(e, "status", None) == 429:
                        res["shed"] += 1
                    else:
                        res["errors"] += 1
                        if len(error_samples) < 5:
                            error_samples.append(repr(e))
                continue
            dt = clock() - t0
            with lock:
                res["sent"] += 1
                res["ok"] += 1
                latencies.append(dt)

    threads = [threading.Thread(target=worker, daemon=True,
                                name=f"openloop-{k}")
               for k in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = clock() - start
    res.update({
        "offered": len(sched),
        "duration": wall,
        "goodput": res["ok"] / wall if wall > 0 else 0.0,
        "p50": _percentile(latencies, 0.50),
        "p99": _percentile(latencies, 0.99),
        "error_samples": error_samples,
    })
    return res
