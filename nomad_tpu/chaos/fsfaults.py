"""Disk fault injection for the durable raft storage.

The durable layer funnels its writes through two chokepoints —
`utils.files.atomic_write_text` (stable store, snapshots) and
`DurableLog._write` (log appends) — and both call
`utils.files.check_fault(op, path)` before touching the disk. That hook
is a no-op until an `FSFaults` shim is installed, at which point armed
faults raise real `OSError`s (ENOSPC, EIO) at the exact write the
scenario scripts.

Ops seen today: "atomic_write_text", "log_append", "log_rewrite",
"snap_chunk" (each chunk write of an incoming install-snapshot
transfer, `raft.durable.FileSnapshotSink.write`).

    faults = FSFaults()
    with faults.installed():
        faults.arm("log_append", errno_=errno.ENOSPC, count=2)
        ...  # the next two log appends fail with ENOSPC

`tear_log_tail` simulates the other classic crash artifact: a torn
(half-written) final line in log.jsonl, which `DurableLog` must drop
with a warning on the next open instead of refusing to start.
"""

from __future__ import annotations

import contextlib
import errno
import os
import threading
from typing import Dict, List, Optional

from ..utils import files as _files


class FaultArmed:
    __slots__ = ("op", "errno_", "count", "path_substr")

    def __init__(self, op: str, errno_: int, count: int,
                 path_substr: Optional[str]):
        self.op = op
        self.errno_ = errno_
        self.count = count
        self.path_substr = path_substr


class FSFaults:
    """Swappable fs fault shim (install/uninstall around a scenario)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._armed: Dict[str, List[FaultArmed]] = {}
        self.stats = {"raised": 0}

    # -- arming --

    def arm(self, op: str, errno_: int = errno.ENOSPC, count: int = 1,
            path_substr: Optional[str] = None) -> None:
        """The next `count` writes of `op` (optionally restricted to
        paths containing path_substr) raise OSError(errno_)."""
        with self._lock:
            self._armed.setdefault(op, []).append(
                FaultArmed(op, errno_, count, path_substr))

    def disarm(self, op: Optional[str] = None) -> None:
        with self._lock:
            if op is None:
                self._armed.clear()
            else:
                self._armed.pop(op, None)

    # -- the hook --

    def __call__(self, op: str, path: str) -> None:
        with self._lock:
            for fault in self._armed.get(op, []):
                if fault.path_substr is not None \
                        and fault.path_substr not in path:
                    continue
                if fault.count <= 0:
                    continue
                fault.count -= 1
                self.stats["raised"] += 1
                raise OSError(fault.errno_,
                              f"{os.strerror(fault.errno_)} "
                              f"(chaos-injected, op={op})", path)

    # -- lifecycle --

    def install(self) -> "FSFaults":
        _files.set_fault_hook(self)
        return self

    def uninstall(self) -> None:
        _files.set_fault_hook(None)

    @contextlib.contextmanager
    def installed(self):
        self.install()
        try:
            yield self
        finally:
            self.uninstall()


def tear_log_tail(raft_dir: str, garbage: str = '{"index": 999, "ter') -> str:
    """Append a torn half-line to <raft_dir>/log.jsonl, as a crash
    mid-append would leave it. Returns the path."""
    path = os.path.join(raft_dir, "log.jsonl")
    with open(path, "a") as f:
        f.write(garbage)
    return path


def truncate_log_mid_line(raft_dir: str, cut_bytes: int = 7) -> str:
    """Truncate log.jsonl `cut_bytes` short of its end — a torn tail
    with no newline, the other shape a crashed append leaves."""
    path = os.path.join(raft_dir, "log.jsonl")
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(0, size - cut_bytes))
    return path
