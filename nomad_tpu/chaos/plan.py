"""Deterministic per-message fault plan for the raft transports.

Both `InProcTransport` and `SocketTransport` consult an attached
FaultPlan on every outgoing raft message. The plan can:

- cut **directed** links (src -> dst) or whole nodes, optionally with a
  clock-based expiry (auto-heal);
- drop, delay, duplicate, or reorder messages probabilistically per
  link rule.

Determinism: each (src, dst) link keeps a message counter, and the
verdict for message #n derives from `sha256(seed:src>dst:n)` — a fixed
seed reproduces the same per-link drop/delay/duplicate pattern
regardless of thread interleaving. Scripted cuts are exact. The seed
comes from `NOMAD_TPU_CHAOS_SEED` when the runner builds the plan (see
ROBUSTNESS.md for the reproduction workflow).

Virtual time: the plan reads time only through `self.clock` (default
`time.monotonic`), so tests may inject a virtual clock and expiring
cuts / delay windows follow it deterministically.
"""

from __future__ import annotations

import hashlib
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional, Tuple


@dataclass
class LinkFaults:
    """Per-link probabilistic fault rule. Probabilities are independent
    per message; `delay_range` applies when the delay roll hits."""
    drop: float = 0.0        # lose the message (sender sees a lost reply)
    delay: float = 0.0       # stall the send in-line for delay_range s
    duplicate: float = 0.0   # deliver again asynchronously a bit later
    reorder: float = 0.0     # deliver asynchronously after delay_range,
    #                          returning loss to the sender — the message
    #                          arrives late, out of order with successors
    delay_range: Tuple[float, float] = (0.005, 0.05)


@dataclass
class Verdict:
    """What the transport should do with one message."""
    drop: bool = False
    delay: float = 0.0           # sleep before synchronous delivery
    duplicate_after: float = 0.0  # >0: also deliver a copy this much later
    reorder_after: float = 0.0    # >0: deliver ONLY asynchronously after
    #                               this delay; sender sees message loss


_DELIVER = Verdict()


@dataclass
class _Cut:
    expires_at: Optional[float] = None  # plan-clock time; None = until heal


class FaultPlan:
    """Seeded, virtual-time-aware fault schedule (see module docstring).

    Thread-safe: transports call decide() from raft tick / RPC threads
    while the scenario runner mutates the rule set.
    """

    def __init__(self, seed: int = 0,
                 clock: Callable[[], float] = time.monotonic):
        self.seed = seed
        self.clock = clock
        self._lock = threading.Lock()
        self._cut_links: Dict[Tuple[str, str], _Cut] = {}
        self._cut_nodes: Dict[str, _Cut] = {}
        # (src|None, dst|None) -> rule; None is a wildcard side
        self._rules: Dict[Tuple[Optional[str], Optional[str]], LinkFaults] = {}
        self._counters: Dict[Tuple[str, str], int] = {}
        self.stats: Dict[str, int] = {
            "delivered": 0, "cut": 0, "dropped": 0, "delayed": 0,
            "duplicated": 0, "reordered": 0}

    # -- scripted cuts --

    def cut_link(self, src: str, dst: str,
                 for_s: Optional[float] = None) -> None:
        """Cut the directed link src -> dst (dst -> src stays up)."""
        expires = None if for_s is None else self.clock() + for_s
        with self._lock:
            self._cut_links[(src, dst)] = _Cut(expires)

    def heal_link(self, src: str, dst: str) -> None:
        with self._lock:
            self._cut_links.pop((src, dst), None)

    def cut_node(self, node_id: str, for_s: Optional[float] = None) -> None:
        """Cut every link to and from node_id (symmetric isolation)."""
        expires = None if for_s is None else self.clock() + for_s
        with self._lock:
            self._cut_nodes[node_id] = _Cut(expires)

    def heal_node(self, node_id: str) -> None:
        with self._lock:
            self._cut_nodes.pop(node_id, None)

    def heal_all(self) -> None:
        """Heal every cut; probabilistic rules stay (clear_faults)."""
        with self._lock:
            self._cut_links.clear()
            self._cut_nodes.clear()

    # -- probabilistic rules --

    def set_link_faults(self, src: Optional[str] = None,
                        dst: Optional[str] = None,
                        faults: Optional[LinkFaults] = None,
                        **kw) -> None:
        """Attach a fault rule to a link; None on either side is a
        wildcard (set_link_faults(drop=0.1) faults every link)."""
        with self._lock:
            self._rules[(src, dst)] = faults if faults is not None \
                else LinkFaults(**kw)

    def clear_faults(self) -> None:
        with self._lock:
            self._rules.clear()

    def quiesce(self) -> None:
        """Heal everything — cuts and probabilistic rules."""
        with self._lock:
            self._cut_links.clear()
            self._cut_nodes.clear()
            self._rules.clear()

    # -- the per-message verdict --

    def _cut_active_locked(self, cut: Optional[_Cut], now: float) -> bool:
        if cut is None:
            return False
        return cut.expires_at is None or now < cut.expires_at

    def decide(self, src: str, dst: str, msg: Optional[dict] = None) -> Verdict:
        now = self.clock()
        with self._lock:
            if (self._cut_active_locked(self._cut_nodes.get(src), now)
                    or self._cut_active_locked(self._cut_nodes.get(dst), now)
                    or self._cut_active_locked(
                        self._cut_links.get((src, dst)), now)):
                self.stats["cut"] += 1
                return Verdict(drop=True)
            rule = (self._rules.get((src, dst))
                    or self._rules.get((None, dst))
                    or self._rules.get((src, None))
                    or self._rules.get((None, None)))
            if rule is None:
                self.stats["delivered"] += 1
                return _DELIVER
            n = self._counters.get((src, dst), 0)
            self._counters[(src, dst)] = n + 1
        # three independent uniform rolls + a delay magnitude, all derived
        # from (seed, link, n) so replays are interleaving-independent
        u = _hash_uniforms(self.seed, src, dst, n, 4)
        lo, hi = rule.delay_range
        span = lo + (hi - lo) * u[3]
        with self._lock:
            if u[0] < rule.drop:
                self.stats["dropped"] += 1
                return Verdict(drop=True)
            if u[0] < rule.drop + rule.reorder:
                self.stats["reordered"] += 1
                return Verdict(reorder_after=span)
            v = Verdict()
            if u[1] < rule.delay:
                v.delay = span
                self.stats["delayed"] += 1
            if u[2] < rule.duplicate:
                v.duplicate_after = max(span, 0.005)
                self.stats["duplicated"] += 1
            if not v.delay and not v.duplicate_after:
                self.stats["delivered"] += 1
            return v

    def snapshot_stats(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.stats)


def _hash_uniforms(seed: int, src: str, dst: str, n: int,
                   count: int) -> list:
    """`count` uniforms in [0,1) from a stable hash of the message
    coordinates (thread-interleaving-independent determinism)."""
    h = hashlib.sha256(f"{seed}:{src}>{dst}:{n}".encode()).digest()
    out = []
    for i in range(count):
        chunk = h[i * 8:(i + 1) * 8]
        out.append(int.from_bytes(chunk, "big") / 2 ** 64)
    return out
