"""Client-plane swarm: fleet-scale simulated nodes.

A SimNode speaks the REAL node RPC surface — register / heartbeat /
alloc-ack / deregister — without running tasks, so one process can
sustain 50–100K nodes against a live cluster while the e2e write
pipeline runs. The design constraints:

  * No thread per node. A few driver threads each own a slice of the
    fleet, organized as a time wheel: the slice is spread across S
    slots, every `interval / S` seconds one slot's nodes heartbeat in
    `heartbeat_batch` chunks. Heartbeat load is phase-staggered by
    construction, like a real fleet's jittered check-ins.

  * No per-node RPC. Registration, heartbeats, and alloc acks all ride
    the batch endpoints (`register_nodes`, `heartbeat_batch`,
    `update_allocs_from_client`).

  * Failover-transparent. Every batch re-resolves the entry server via
    `entry_fn` (e.g. `cluster.leader()`), and a failed batch is simply
    a missed beat — the TTL plus the new leader's grace window absorb
    it, which is exactly the property check_node_liveness audits.

`last_ok` per node records the wall-clock time of the last
SERVER-ACKNOWLEDGED heartbeat; the liveness invariant uses it to prove
every down-mark corresponds to a real silence >= TTL.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Set

from ..structs import enums
from ..structs.node import Node
from ..structs.resources import NodeResources

_SIM_ATTRS = {
    "kernel.name": "linux",
    "arch": "x86_64",
    "cpu.arch": "amd64",
    "nomad.version": "0.1.0",
    "driver.mock": "1",
}


def make_sim_node(index: int, prefix: str = "sim") -> Node:
    """A lightweight but fully real Node row (mock.node minus the
    per-node attribute churn — 100K of these must build in seconds)."""
    return Node(
        id=f"{prefix}-{index:06d}",
        name=f"{prefix}-{index}",
        datacenter="dc1",
        attributes=dict(_SIM_ATTRS),
        resources=NodeResources(cpu=4000, memory_mb=8192, disk_mb=102400,
                                total_cores=4),
        drivers={"mock": True, "exec": True},
        status=enums.NODE_STATUS_READY,
    )


class SimNode:
    __slots__ = ("id", "node", "last_ok", "beats", "silenced", "registered")

    def __init__(self, node: Node):
        self.id = node.id
        self.node = node
        self.last_ok = 0.0     # wall clock of last server-acked heartbeat
        self.beats = 0
        self.silenced = False
        self.registered = False


class Swarm:
    def __init__(self, entry_fn: Callable[[], object], count: int,
                 ttl: float, interval: Optional[float] = None,
                 drivers: int = 4, rpc_batch: int = 512,
                 prefix: str = "sim", ack: bool = False):
        self.entry_fn = entry_fn
        self.ttl = ttl
        self.interval = interval if interval is not None else ttl / 3.0
        self.rpc_batch = max(1, rpc_batch)
        self.ack_enabled = ack
        first = make_sim_node(0, prefix)
        first.compute_class()
        self.nodes: List[SimNode] = [SimNode(first)]
        for i in range(1, count):
            n = make_sim_node(i, prefix)
            # identical scheduling-relevant fields => identical class;
            # skip re-hashing it 100K times
            n.computed_class = first.computed_class
            self.nodes.append(SimNode(n))
        self._by_id: Dict[str, SimNode] = {sn.id: sn for sn in self.nodes}
        self._lock = threading.Lock()   # guards SimNode flags + stats
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._drivers = max(1, drivers)
        self.stats = {"heartbeats": 0, "hb_failures": 0, "acked": 0,
                      "ack_failures": 0, "registered": 0, "deregistered": 0}
        self.acked_ids: Set[str] = set()

    # -- registration ------------------------------------------------

    def register_all(self, chunk: int = 2000, deadline_s: float = 180.0,
                     subset: Optional[List[SimNode]] = None) -> int:
        """Register the fleet in `register_nodes` chunks, retrying each
        chunk through elections until the deadline."""
        import copy as _copy

        sims = subset if subset is not None else self.nodes
        deadline = time.time() + deadline_s
        done = 0
        for start in range(0, len(sims), chunk):
            batch = sims[start:start + chunk]
            while True:
                try:
                    entry = self.entry_fn()
                    if entry is None:
                        raise ConnectionError("no live server")
                    # register COPIES: in-proc the store takes ownership
                    # of the row object; the swarm's copy stays ours to
                    # re-register during churn
                    entry.register_nodes([_copy.copy(sn.node)
                                          for sn in batch])
                    break
                except Exception:
                    if time.time() > deadline or self._stop.wait(0.25):
                        return done
            now = time.time()
            with self._lock:
                for sn in batch:
                    sn.registered = True
                    sn.last_ok = now
                self.stats["registered"] += len(batch)
            done += len(batch)
        return done

    def deregister(self, sims: List[SimNode]) -> int:
        done = 0
        for sn in sims:
            try:
                entry = self.entry_fn()
                if entry is None:
                    raise ConnectionError("no live server")
                entry.deregister_node(sn.id)
            except Exception:
                continue
            with self._lock:
                sn.registered = False
                self.stats["deregistered"] += 1
            done += 1
        return done

    # -- heartbeat drivers -------------------------------------------

    def start(self) -> None:
        self._stop.clear()
        for d in range(self._drivers):
            sims = self.nodes[d::self._drivers]
            t = threading.Thread(target=self._run_driver, args=(sims,),
                                 daemon=True, name=f"swarm-driver-{d}")
            t.start()
            self._threads.append(t)
        if self.ack_enabled:
            t = threading.Thread(target=self._run_acks, daemon=True,
                                 name="swarm-acks")
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads.clear()

    def _run_driver(self, sims: List[SimNode]) -> None:
        # time wheel: spread the slice over S slots; each tick fires one
        # slot, so every node beats once per interval, phase-staggered
        slots_n = max(2, min(32, int(self.interval / 0.1) or 2))
        slots: List[List[SimNode]] = [[] for _ in range(slots_n)]
        for i, sn in enumerate(sims):
            slots[i % slots_n].append(sn)
        tick = self.interval / slots_n
        cursor = 0
        next_t = time.time() + tick
        while not self._stop.is_set():
            delay = next_t - time.time()
            if delay > 0:
                if self._stop.wait(delay):
                    return
            elif delay < -self.interval:
                next_t = time.time()   # fell a whole interval behind
            next_t += tick
            slot = slots[cursor]
            cursor = (cursor + 1) % slots_n
            with self._lock:
                due = [sn for sn in slot
                       if sn.registered and not sn.silenced]
            for start in range(0, len(due), self.rpc_batch):
                chunk = due[start:start + self.rpc_batch]
                try:
                    entry = self.entry_fn()
                    if entry is None:
                        raise ConnectionError("no live server")
                    entry.heartbeat_batch([sn.id for sn in chunk])
                except Exception:
                    # missed beat: TTL + failover grace absorb it
                    with self._lock:
                        self.stats["hb_failures"] += 1
                    continue
                now = time.time()
                with self._lock:
                    for sn in chunk:
                        sn.last_ok = now
                        sn.beats += 1
                    self.stats["heartbeats"] += len(chunk)

    # -- alloc acks (the client-ack half of the RPC surface) ---------

    def _hub_owner(self):
        """The core Server whose AllocSyncHub is live (the leader's)."""
        try:
            s = self.entry_fn()
        except Exception:
            return None
        if s is None:
            return None
        core = getattr(s, "server", s)
        hub = getattr(core, "alloc_sync", None)
        if hub is not None and hub.running:
            return core
        return None

    def _run_acks(self) -> None:
        """Subscribe ONE delta feed covering the whole fleet and ack
        every alloc pushed to a sim node: desired-run allocs ack
        `running`, stop/evict-desired allocs ack `complete` (the drain
        path needs a client-side terminal ack to converge)."""
        owner = None
        sub = None
        rescan = True
        while not self._stop.is_set():
            cur = self._hub_owner()
            if cur is not owner or sub is None or sub.closed:
                if sub is not None:
                    sub.close()
                owner = cur
                sub = None
                if owner is None:
                    if self._stop.wait(0.2):
                        return
                    continue
                sub = owner.alloc_sync.subscribe(list(self._by_id))
                rescan = True
            batch, resync = sub.poll(timeout=0.25)
            if self._stop.is_set():
                return
            if resync or rescan:
                rescan = False
                try:
                    entry = self.entry_fn()
                    snap = entry.store.snapshot()
                    batch = [a for a in snap.allocs()
                             if a.node_id in self._by_id]
                except Exception:
                    rescan = True
                    continue
            if batch:
                self._ack(batch)

    def _ack(self, allocs: List) -> None:
        updates = []
        for a in allocs:
            if a.client_terminal():
                continue
            if a.desired_status == enums.ALLOC_DESIRED_RUN:
                status = enums.ALLOC_CLIENT_RUNNING
                if a.client_status == status:
                    continue
            else:
                status = enums.ALLOC_CLIENT_COMPLETE
            upd = a.copy_for_update()
            upd.client_status = status
            updates.append(upd)
        for start in range(0, len(updates), self.rpc_batch):
            chunk = updates[start:start + self.rpc_batch]
            try:
                entry = self.entry_fn()
                if entry is None:
                    raise ConnectionError("no live server")
                entry.update_allocs_from_client(chunk)
            except Exception:
                with self._lock:
                    self.stats["ack_failures"] += 1
                continue
            with self._lock:
                self.stats["acked"] += len(chunk)
                self.acked_ids.update(u.id for u in chunk)

    # -- silence / flap controls -------------------------------------

    def silence(self, sims: List[SimNode]) -> None:
        with self._lock:
            for sn in sims:
                sn.silenced = True

    def unsilence(self, sims: List[SimNode]) -> None:
        with self._lock:
            for sn in sims:
                sn.silenced = False

    # -- accessors for the liveness invariant ------------------------

    def ids(self) -> Set[str]:
        return set(self._by_id)

    def sim(self, node_id: str) -> Optional[SimNode]:
        return self._by_id.get(node_id)

    def last_ok(self, node_id: str) -> float:
        sn = self._by_id.get(node_id)
        if sn is None:
            return 0.0
        with self._lock:
            return sn.last_ok

    def total_beats(self) -> int:
        with self._lock:
            return self.stats["heartbeats"]
