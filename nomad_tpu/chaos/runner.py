"""Scenario runner: scripted fault sequences with invariants between steps.

A scenario is an ordered list of named steps — plain callables that
poke the cluster (cut links, crash servers, write data, heal). After
every step the runner sweeps the history invariants (election safety,
log matching, committed durability); liveness checks (convergence,
reschedule) are steps themselves, placed where the scenario expects
quiescence.

Determinism: the fault seed comes from ``NOMAD_TPU_CHAOS_SEED``
(default 0). Every probabilistic verdict a ``FaultPlan`` hands out is
derived by hashing (seed, link, per-link message counter), so a failing
run replays with::

    NOMAD_TPU_CHAOS_SEED=1234 python -m pytest tests/test_chaos.py -x
"""

from __future__ import annotations

import logging
import os
import time
from typing import Callable, List, Optional, Tuple

from .invariants import InvariantChecker, InvariantViolation
from .plan import FaultPlan

log = logging.getLogger("nomad_tpu.chaos")

__all__ = ["ScenarioRunner", "seed_from_env", "InvariantViolation"]


def seed_from_env(default: int = 0) -> int:
    raw = os.environ.get("NOMAD_TPU_CHAOS_SEED", "")
    try:
        return int(raw) if raw else default
    except ValueError:
        log.warning("NOMAD_TPU_CHAOS_SEED=%r is not an int; using %d",
                    raw, default)
        return default


class ScenarioRunner:
    """Drive one scripted scenario against a live RaftCluster.

    The runner wires a seeded FaultPlan into the cluster transport,
    executes steps in order, and runs the safety sweep after each one.
    ``quiesce()`` before teardown clears every standing fault so the
    cluster can converge (and late Timer deliveries become no-ops).
    """

    def __init__(self, cluster, seed: Optional[int] = None,
                 checker: Optional[InvariantChecker] = None):
        self.cluster = cluster
        self.seed = seed_from_env() if seed is None else seed
        self.plan = FaultPlan(seed=self.seed)
        self.checker = checker or InvariantChecker()
        self._steps: List[Tuple[str, Callable[["ScenarioRunner"], None]]] = []
        self.report = {"seed": self.seed, "steps": []}
        if hasattr(cluster.transport, "set_fault_plan"):
            cluster.transport.set_fault_plan(self.plan)

    def add(self, name: str,
            fn: Callable[["ScenarioRunner"], None]) -> "ScenarioRunner":
        self._steps.append((name, fn))
        return self

    def step(self, name: str):
        """Decorator form: @runner.step("cut leader->follower")."""
        def register(fn):
            self.add(name, fn)
            return fn
        return register

    def run(self) -> dict:
        log.info("scenario start: %d step(s), seed=%d",
                 len(self._steps), self.seed)
        try:
            for name, fn in self._steps:
                t0 = time.monotonic()
                fn(self)
                self.checker.check_all(self.cluster)
                dt = time.monotonic() - t0
                self.report["steps"].append({"name": name,
                                             "seconds": round(dt, 3)})
                log.info("step ok (%.2fs): %s", dt, name)
        finally:
            self.quiesce()
            self.report["faults"] = self.plan.snapshot_stats()
            self.report["invariants"] = dict(self.checker.stats)
        return self.report

    def quiesce(self) -> None:
        """Clear all faults so teardown/convergence isn't fighting the
        plan: heal cuts, zero probabilities, heal transport links."""
        self.plan.quiesce()
        if hasattr(self.cluster.transport, "heal"):
            self.cluster.transport.heal()

    # -- step helpers (the verbs scenarios are written in) -----------

    def heal_and_converge(self, timeout: float = 15.0) -> None:
        self.quiesce()
        self.checker.check_convergence(self.cluster, timeout=timeout)

    def wait_for_leader(self, timeout: float = 10.0):
        leader = self.cluster.wait_for_leader(timeout=timeout)
        if leader is None:
            raise InvariantViolation(
                f"no leader elected within {timeout:.0f}s "
                f"(seed={self.seed})")
        return leader
