"""Safety invariants checked between chaos steps.

Four checks, mirroring the safety arguments in raft (Ongaro §5.2/§5.4)
and the reference scheduler's liveness contract:

  1. election safety — at most one leader per term, ever, across the
     whole run (crash/restart included);
  2. log matching — any two live nodes agree on (term, command) for
     every index both have committed;
  3. committed durability — once an entry is observed committed it is
     never lost or rewritten, across crashes and restarts (snapshot
     compaction counts as retention, not loss);
  4. convergence / reschedule — after a heal, every FSM reaches the
     same state, and every alloc on a heartbeat-invalidated node is
     eventually rescheduled off it.

The checker is stateful on purpose: election safety and durability are
*history* properties, so the same ``InvariantChecker`` must live for a
whole scenario and see every intermediate state the runner produces.
All reads snapshot one node at a time under that node's own lock —
never two node locks at once, so the checker cannot introduce a
lock-order cycle into the raft graph nomadsan watches.
"""

from __future__ import annotations

import hashlib
import json
import logging
import time
from typing import Dict, List, Optional, Tuple

from ..structs import enums

log = logging.getLogger("nomad_tpu.chaos")


class InvariantViolation(AssertionError):
    """A safety property was broken; chaos runs must fail loudly."""


def _digest(command) -> str:
    """Interleaving- and storage-independent fingerprint of a command.

    json round-trips tuples to lists, so an in-memory node (tuples) and
    a durably restarted one (lists from log.jsonl) digest identically.
    """
    payload = json.dumps(command, sort_keys=True, default=str)
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def _live(cluster) -> List:
    return [s for s in cluster.servers.values()
            if not s.crashed and not s.raft._stop.is_set()]


def _log_prefix(server, committed_only: bool = True,
                ) -> Tuple[int, int, List[Tuple[int, int, str]]]:
    """(first_index, commit_index, [(index, term, digest), ...]) for the
    entries this node holds in its log — the committed prefix by
    default, or the whole log (``committed_only=False``; used by the
    durability check because commit *knowledge* is volatile: a restarted
    leader re-derives commit_index after election while its log already
    holds everything). Entries below first_index were compacted into a
    snapshot — covered, not lost."""
    raft = server.raft
    with raft._lock:
        last = raft.log.last()[0]
        commit = min(raft.commit_index, last)
        first = raft.log.first_index() if hasattr(raft.log, "first_index") else 1
        upto = last if not committed_only else commit
        rows = []
        for idx in range(first, upto + 1):
            e = raft.log.get(idx)
            if e is None:  # compacted under us; harmless
                continue
            rows.append((idx, e.term, _digest(e.command)))
    return first, commit, rows


def _dump_comparable(server) -> dict:
    """FSM dump minus the MVCC index: a restarted replica that restored
    a snapshot and replayed the tail holds identical *contents* at a
    possibly different generation counter."""
    from ..state.persist import dump_store
    d = dump_store(server.local_store)
    d.pop("index", None)
    return d


class InvariantChecker:
    def __init__(self):
        # term -> leader id, accumulated over the whole scenario
        self._leaders_by_term: Dict[int, str] = {}
        # index -> (term, digest) once observed committed anywhere
        self._committed: Dict[int, Tuple[int, str]] = {}
        self.stats = {"checks": 0, "violations": 0}

    # -- 1: election safety ------------------------------------------

    def check_election_safety(self, cluster) -> None:
        for s in _live(cluster):
            raft = s.raft
            with raft._lock:
                is_leader = raft.state == "leader"
                term = raft.current_term
            if not is_leader:
                continue
            prev = self._leaders_by_term.get(term)
            if prev is not None and prev != s.id:
                self._fail(
                    f"election safety: term {term} has two leaders "
                    f"({prev} and {s.id})")
            self._leaders_by_term[term] = s.id

    # -- 2: log matching ---------------------------------------------

    def check_log_matching(self, cluster) -> None:
        prefixes = [(s.id, _log_prefix(s)) for s in _live(cluster)]
        by_index: Dict[int, Tuple[str, int, str]] = {}
        for sid, (_first, _commit, rows) in prefixes:
            for idx, term, dig in rows:
                seen = by_index.get(idx)
                if seen is None:
                    by_index[idx] = (sid, term, dig)
                elif (term, dig) != seen[1:]:
                    self._fail(
                        f"log matching: committed index {idx} diverges — "
                        f"{seen[0]} has (term={seen[1]}, {seen[2]}), "
                        f"{sid} has (term={term}, {dig})")

    # -- 3: committed entries survive crashes ------------------------

    def check_committed_durability(self, cluster) -> None:
        """Record every committed (index, term, digest) seen so far and
        verify all previous records are still held (or snapshotted) by
        at least one live node, unchanged.

        Records come from committed prefixes; the retention check scans
        whole logs: raft only guarantees committed entries are present
        in a quorum's LOGS — commit_index itself is volatile knowledge
        every node re-derives after an election, so right after a
        leader crash no live node may *know* the commit point yet."""
        live = _live(cluster)
        full = {s.id: _log_prefix(s, committed_only=False) for s in live}
        maps = {sid: {idx: (term, dig) for idx, term, dig in rows}
                for sid, (_f, _c, rows) in full.items()}
        for sid, (_f, commit, rows) in full.items():
            for idx, term, dig in rows:
                if idx > commit:
                    continue  # record only what this node knows committed
                prev = self._committed.get(idx)
                if prev is not None and prev != (term, dig):
                    self._fail(
                        f"durability: committed index {idx} rewritten — "
                        f"recorded (term={prev[0]}, {prev[1]}), {sid} now "
                        f"has (term={term}, {dig})")
                self._committed[idx] = (term, dig)
        if not live:
            return
        for idx, (term, dig) in self._committed.items():
            held = False
            for sid, (first, _commit, _rows) in full.items():
                if idx < first:
                    held = True  # compacted into this node's snapshot
                    break
                if maps[sid].get(idx) == (term, dig):
                    held = True
                    break
            if not held:
                self._fail(
                    f"durability: committed index {idx} (term={term}, "
                    f"{dig}) vanished from every live node")

    # -- 4a: FSM convergence after heal ------------------------------

    def check_convergence(self, cluster, timeout: float = 15.0) -> None:
        """After a heal: all live nodes apply up to the max commit index
        and hold identical FSM contents."""
        deadline = time.monotonic() + timeout
        last_err = "no live nodes"
        while time.monotonic() < deadline:
            live = _live(cluster)
            if not live:
                break
            target = max(s.raft.commit_index for s in live)
            lagging = [s.id for s in live if s.raft.last_applied < target]
            if lagging:
                last_err = (f"replicas {lagging} applied < commit "
                            f"index {target}")
                time.sleep(0.05)
                continue
            dumps = {s.id: _dump_comparable(s) for s in live}
            ref_id = live[0].id
            ref = dumps[ref_id]
            diverged = [sid for sid, d in dumps.items() if d != ref]
            if not diverged:
                self.stats["checks"] += 1
                return
            last_err = f"FSM contents of {diverged} differ from {ref_id}"
            time.sleep(0.05)
        self._fail(f"convergence: {last_err} after {timeout:.0f}s")

    # -- 4b: allocs leave heartbeat-invalidated nodes ----------------

    def check_reschedule(self, server, timeout: float = 15.0) -> None:
        """Every alloc placed on a node the heartbeat manager marked
        down must eventually stop being live there (lost/stopped, with
        the scheduler free to place replacements elsewhere)."""
        from ..structs import enums
        deadline = time.monotonic() + timeout
        last_err = ""
        while time.monotonic() < deadline:
            snap = server.store.snapshot()
            down = [n.id for n in snap.nodes()
                    if n.status == enums.NODE_STATUS_DOWN]
            stranded = []
            for nid in down:
                for a in snap.allocs_by_node(nid):
                    if not a.terminal_status() and not a.server_terminal():
                        stranded.append((a.id[:8], nid))
            if not stranded:
                self.stats["checks"] += 1
                return
            last_err = f"live allocs still on down nodes: {stranded}"
            time.sleep(0.05)
        self._fail(f"reschedule: {last_err} after {timeout:.0f}s")

    # -- 5: alloc-set uniqueness -------------------------------------

    def check_alloc_uniqueness(self, cluster) -> None:
        """No duplicate placements: on every live node's FSM, at most
        one *live* (neither client- nor server-terminal) alloc exists
        per (namespace, job_id, alloc name). The batched plan-commit
        path re-applies ambiguous rounds through the idempotent per-plan
        fallback after a failover — upserts keyed by alloc id converge,
        so a duplicate under a FRESH id is exactly the bug class this
        catches (a round answered twice re-planning the same slot)."""
        for s in _live(cluster):
            snap = s.local_store.snapshot()
            by_slot: Dict[tuple, List[str]] = {}
            for a in snap.allocs():
                if a.terminal_status() or a.server_terminal():
                    continue
                by_slot.setdefault(
                    (a.namespace, a.job_id, a.name), []).append(a.id)
            dups = {slot: ids for slot, ids in by_slot.items()
                    if len(ids) > 1}
            if dups:
                worst = next(iter(dups.items()))
                self._fail(
                    f"alloc uniqueness: {len(dups)} slot(s) on {s.id} "
                    f"hold multiple live allocs, e.g. {worst[0]} -> "
                    f"{[i[:8] for i in worst[1]]}")
        self.stats["checks"] += 1

    # -- 8: node liveness (client-plane swarm) ------------------------

    def check_node_liveness(self, cluster, swarm=None,
                            ttl: float = None) -> None:
        """No missed-TTL false positives, on every live replica:

        (a) every expiry the heartbeat manager fired is attributable to
            a real silence — its attribution log shows >= ~one full TTL
            between arming and expiry (the failover grace window makes
            this hold across restore() too);
        (b) with a swarm attached: any swarm node marked down/
            disconnected went at least ~one TTL without a server-acked
            heartbeat before the mark (`status_updated_at - last_ok`);
        (c) no node is both down and heartbeating: a down-marked node
            whose heartbeats have been succeeding for > 2 TTLs since
            the mark should have flipped back to ready.

        Accepts a RaftCluster or a single (possibly replicated)
        server. Small epsilons absorb clock skew between the proposer's
        wall-clock stamp and the swarm's ack timestamps."""
        down_states = (enums.NODE_STATUS_DOWN,
                       enums.NODE_STATUS_DISCONNECTED)
        servers = (_live(cluster) if hasattr(cluster, "servers")
                   else [cluster])
        for s in servers:
            core = getattr(s, "server", s)
            store = getattr(s, "local_store", None) or core.store
            mgr = core.heartbeats
            t = ttl if ttl is not None else mgr.ttl
            for node_id, armed_at, expired_at in mgr.expiry_snapshot():
                silence = expired_at - armed_at
                if silence < t * 0.95 - 0.01:
                    self._fail(
                        f"node liveness: {getattr(s, 'id', 'server')} "
                        f"expired {node_id} after only {silence:.3f}s "
                        f"of a {t:.3f}s TTL")
            if swarm is None:
                continue
            now = time.time()
            for node in store.snapshot().nodes():
                sn = swarm.sim(node.id)
                if sn is None or node.status not in down_states:
                    continue
                last_ok = swarm.last_ok(node.id)
                silence = node.status_updated_at - last_ok
                if last_ok > 0 and silence < t * 0.9 - 0.1:
                    self._fail(
                        f"node liveness: {node.id} marked {node.status} "
                        f"on {getattr(s, 'id', 'server')} only "
                        f"{silence:.3f}s after a server-acked heartbeat "
                        f"(TTL {t:.3f}s) — missed-TTL false positive")
                if (last_ok - node.status_updated_at > 2 * t
                        and now - last_ok < t):
                    self._fail(
                        f"node liveness: {node.id} is {node.status} on "
                        f"{getattr(s, 'id', 'server')} yet has been "
                        f"heartbeating successfully for "
                        f"{last_ok - node.status_updated_at:.3f}s since "
                        f"the mark — down AND heartbeating")
        self.stats["checks"] += 1

    # -- 6: snapshot integrity (nomadown runtime prong) ---------------

    def check_snapshot_integrity(self, cluster=None) -> None:
        """When the nomadown ownership sanitizer is armed
        (NOMAD_TPU_SAN=1), sweep every fingerprinted store row for
        post-insert divergence — an aliased mutation rewrites MVCC
        history for all live snapshots and, through the FSM, diverges
        replicas; catch it here before it surfaces as a log-matching or
        convergence failure."""
        from ..analysis.ownership import GLOBAL as own

        if not own.active:
            return
        before = len(own.violations)
        own.verify_all()
        fresh = own.violations[before:]
        if fresh:
            extra = f" (+{len(fresh) - 1} more)" if len(fresh) > 1 else ""
            self._fail(f"snapshot integrity: {fresh[0].render()}{extra}")

    # -- 7: launch ledger (nomadjit runtime prong) --------------------

    def check_launch_ledger(self, cluster=None) -> None:
        """When the nomadjit launch ledger is armed (NOMAD_TPU_SAN=1),
        sweep it for warm-path compiles, extra host syncs, unsanctioned
        transfers, and leaked launch windows — a retrace or stray sync
        on the solve hot path bills milliseconds to every launch long
        before it surfaces as a failed perf gate."""
        from ..analysis.launch_ledger import GLOBAL as ledger

        if not ledger.active:
            return
        problems = ledger.verify_all()
        if problems:
            extra = (f" (+{len(problems) - 1} more)"
                     if len(problems) > 1 else "")
            self._fail(f"launch ledger: {problems[0]}{extra}")

    # -- 9: event completeness (nomadflow runtime prong) ---------------

    def check_event_completeness(self, cluster=None) -> None:
        """When the nomadflow shadow tracker is armed (NOMAD_TPU_SAN=1),
        force-compare every attached shadow replica against a fresh MVCC
        snapshot rebuild — a mutation that skipped its delta leaves every
        event consumer (alloc sync, the event stream API, the future
        device-resident incremental state) silently stale; catch the
        missing event here, at the commit that dropped it."""
        from ..analysis.shadow import GLOBAL as shadow

        if not shadow.active:
            return
        before = len(shadow.violations)
        shadow.verify_all()
        fresh = shadow.violations[before:]
        if fresh:
            extra = f" (+{len(fresh) - 1} more)" if len(fresh) > 1 else ""
            self._fail(f"event completeness: {fresh[0].render()}{extra}")

    # -- 11: incremental-state parity (nomadstate) ---------------------

    def check_state_parity(self, cluster=None) -> None:
        """Force a parity digest on every attached incremental-state
        feed (tensor/incremental.py): the delta-fed device-resident
        usage base must equal a fresh gen-bounded snapshot rebuild
        bit-exactly, flushed device twins included. Unlike the shadow
        prong the feeds attach in production, so this sweep runs
        whenever any feed exists (NOMAD_TPU_INCR=0 turns each digest
        into a no-op)."""
        from ..tensor.incremental import GLOBAL as state

        if not state.feeds:
            return
        before = len(state.violations)
        state.verify_all()
        fresh = state.violations[before:]
        if fresh:
            extra = f" (+{len(fresh) - 1} more)" if len(fresh) > 1 else ""
            self._fail(f"state parity: {fresh[0].render()}{extra}")

    # -- 10: overload tier ordering (nomadload) ------------------------

    def check_overload_ordering(self, cluster, window: float = 0.5
                                ) -> None:
        """Audit every live server's admission ledger (nomadload): the
        whole point of the overload plane is that liveness traffic
        survives at the expense of bulk traffic, never the reverse.

        (a) a tier-0 (liveness) request was never shed while the server
            was alive — tier-0 sheds are legal only on a stopping
            server (set_alive(False));
        (b) tier ordering: no tier-0 shed has a tier>=2 (submit/read)
            admit within ``window`` seconds of it — bulk work getting
            through while heartbeats bounce is priority inversion.

        Accepts a RaftCluster or a single (possibly replicated)
        server."""
        servers = (_live(cluster) if hasattr(cluster, "servers")
                   else [cluster])
        for s in servers:
            core = getattr(s, "server", s)
            adm = getattr(core, "loadctl", None)
            if adm is None:
                continue
            ledger = adm.ledger()
            t0_sheds = [(ts, src) for ts, tier, kind, src in ledger
                        if tier == 0 and kind == "shed"]
            if adm.snapshot()["alive"] and t0_sheds:
                ts, src = t0_sheds[0]
                self._fail(
                    f"overload ordering: {getattr(s, 'id', 'server')} "
                    f"shed {len(t0_sheds)} tier-0 request(s) while "
                    f"alive (first: source={src})")
            bulk_admits = [ts for ts, tier, kind, _src in ledger
                           if tier >= 2 and kind == "admit"]
            for ts, src in t0_sheds:
                near = [b for b in bulk_admits if abs(b - ts) <= window]
                if near:
                    self._fail(
                        f"overload ordering: "
                        f"{getattr(s, 'id', 'server')} shed a tier-0 "
                        f"request (source={src}) within {window:.1f}s "
                        f"of {len(near)} tier>=2 admit(s) — priority "
                        f"inversion")
        self.stats["checks"] += 1

    # -- aggregate ----------------------------------------------------

    def check_all(self, cluster) -> None:
        """The per-step safety sweep (history properties only; the
        liveness checks — convergence, reschedule — take timeouts and
        run where a scenario expects quiescence)."""
        self.check_snapshot_integrity(cluster)
        self.check_launch_ledger(cluster)
        self.check_event_completeness(cluster)
        self.check_state_parity(cluster)
        self.check_election_safety(cluster)
        self.check_log_matching(cluster)
        self.check_committed_durability(cluster)
        self.check_alloc_uniqueness(cluster)
        self.check_overload_ordering(cluster)
        self.stats["checks"] += 1

    def _fail(self, msg: str) -> None:
        self.stats["violations"] += 1
        log.error("invariant violated: %s", msg)
        # flight recorder: the last few hundred subsystem transitions
        # (broker deliveries, plan verdicts, raft role flips, solver
        # launches) are exactly the forensics a violation needs — dump
        # them with the failure instead of asking for a repro run
        from ..obs import RECORDER

        dump = RECORDER.dump_text(last=80)
        if dump:
            log.error("flight recorder (last 80 events):\n%s", dump)
        raise InvariantViolation(msg)
