"""Chaos smoke: one scripted partition + crash scenario on a durable
3-node cluster, fixed seed, well under a minute.

    python -m nomad_tpu.chaos [--seed N]
    python -m nomad_tpu.chaos --raft-smoke
    python -m nomad_tpu.chaos --e2e-smoke
    python -m nomad_tpu.chaos --solve-smoke
    python -m nomad_tpu.chaos --snap-smoke

Exit 0 when every invariant holds; 2 on a violation (the CI gate in
scripts/check.sh). This is the smallest end-to-end proof that the
fault layer, the recovery paths, and the invariant sweep all work —
the full scenario matrix lives in tests/test_chaos.py.

`--raft-smoke` runs the group-commit write-path smoke instead: 3
durable raft nodes, 500 commands from 8 concurrent proposers, a leader
crash-restart in the middle — asserts zero acknowledged commits lost
(PERF.md "The replicated write path").

`--e2e-smoke` runs the full-pipeline smoke: 300 evals through
broker -> batched workers -> pipelined plan applier -> raft group
commit -> FSM on a durable 3-node cluster, with one leader restart
mid-stream — zero acked allocs lost, rejection <= 5% (the
scripts/check.sh --e2e-smoke gate; PERF.md "End-to-end pipeline").

`--solve-smoke` runs the global-batch solve smoke: bulk-sized jobs
through batched workers under "tpu-solve" on a live 3-node cluster —
asserts a whole worker batch reached the joint auction launch, the
selected packing score dominates the in-launch greedy counterfactual,
and every replica holds a unique alloc set (the scripts/check.sh
--solve-smoke gate; PERF.md "Global-batch solve").

`--snap-smoke` runs the snapshot/compaction smoke: the e2e pipeline on
a durable 3-node cluster with a low snapshot threshold (every replica
snapshots + compacts under load); one follower is crashed and wiped
after the leader compacts, and the restart must catch up via the
chunked install-snapshot path mid-traffic — zero acked-commit loss and
alloc-set uniqueness on every replica (the scripts/check.sh
--snap-smoke gate; ROBUSTNESS.md "Durability at scale")."""

from __future__ import annotations

import argparse
import logging
import sys
import tempfile
import time

from .. import mock
from ..raft.cluster import RaftCluster
from .invariants import InvariantViolation
from .runner import ScenarioRunner, seed_from_env

log = logging.getLogger("nomad_tpu.chaos")


def _live_entry(cluster):
    return next(s for s in cluster.servers.values() if not s.crashed)


def build_scenario(cluster) -> ScenarioRunner:
    r = ScenarioRunner(cluster, seed=seed_from_env())

    @r.step("elect + seed workload")
    def _seed(r):
        leader = r.wait_for_leader()
        entry = _live_entry(cluster)
        for _ in range(2):
            entry.register_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 2
        entry.register_job(job)
        leader.server.wait_for_idle(15.0)

    @r.step("cut the leader's outbound links (directed partition)")
    def _cut(r):
        leader = r.wait_for_leader()
        others = [sid for sid in cluster.servers if sid != leader.id]
        for sid in others:
            cluster.transport.partition_link(leader.id, sid)
        # followers miss heartbeats and elect among themselves; the old
        # leader still hears the higher term and steps down
        deadline = time.time() + 10
        while time.time() < deadline:
            fresh = cluster.leader()
            if fresh is not None and fresh.id != leader.id:
                return
            time.sleep(0.05)
        raise InvariantViolation("no replacement leader after directed cut")

    @r.step("write through the new leader, then heal")
    def _write_and_heal(r):
        entry = _live_entry(cluster)
        entry.register_node(mock.node())
        r.heal_and_converge()

    @r.step("crash the leader mid-write, restart, converge")
    def _crash_restart(r):
        leader = r.wait_for_leader()
        entry = next(s for s in cluster.servers.values()
                     if not s.crashed and s.id != leader.id)
        cluster.crash(leader.id)
        entry.register_node(mock.node())  # forwarded to the new leader
        cluster.restart(leader.id)
        r.heal_and_converge(timeout=20.0)

    return r


def raft_smoke(total: int = 500, proposers: int = 8) -> int:
    """Group-commit smoke: `total` commands through a 3-node durable
    cluster with a leader crash-restart in the middle. Every command
    the proposers saw acknowledged must be present on the post-crash
    leader AND replayed by the restarted node — zero lost commits."""
    import os
    import shutil
    import tempfile
    import threading

    from ..raft.durable import DurableLog
    from ..raft.node import NotLeaderError, RaftNode
    from ..raft.transport import InProcTransport

    t0 = time.monotonic()
    tmp = tempfile.mkdtemp(prefix="nomad-raft-smoke-")
    transport = InProcTransport()
    ids = ["a", "b", "c"]
    applied = {}

    def build(nid: str) -> RaftNode:
        d = os.path.join(tmp, nid)
        os.makedirs(d, exist_ok=True)
        mine = applied[nid] = []  # restart replays into a fresh list
        return RaftNode(nid, ids, transport,
                        lambda cmd, l=mine: l.append(cmd) or len(l),
                        log=DurableLog(d))

    nodes = {nid: build(nid) for nid in ids}
    for n in nodes.values():
        n.start()

    def current_leader(timeout: float = 10.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            for n in nodes.values():
                if n.is_leader():
                    return n
            time.sleep(0.01)
        return None

    try:
        if current_leader() is None:
            print("RAFT SMOKE: FAIL — no leader elected")
            return 2
        acked: set = set()
        acked_lock = threading.Lock()

        def propose(start: int) -> None:
            for i in range(start, total, proposers):
                cmd = ("smoke", (i,), {})
                # an errored apply is AMBIGUOUS (it may still commit);
                # retry until an unambiguous ack — duplicates are fine,
                # the assertion below is set inclusion
                while True:
                    leader = current_leader()
                    if leader is None:
                        time.sleep(0.02)
                        continue
                    try:
                        leader.apply(cmd, timeout=5.0)
                    except (NotLeaderError, TimeoutError):
                        time.sleep(0.01)
                        continue
                    with acked_lock:
                        acked.add(i)
                    break

        threads = [threading.Thread(target=propose, args=(i,), daemon=True)
                   for i in range(proposers)]
        for t in threads:
            t.start()

        # crash the leader mid-stream, then restart it over its data dir
        while True:
            with acked_lock:
                if len(acked) >= total // 2:
                    break
            time.sleep(0.005)
        victim = current_leader()
        if victim is not None:
            vid = victim.id
            transport.unregister(vid)
            victim.stop()
            victim.log.close()
            nodes[vid] = build(vid)
            nodes[vid].start()

        for t in threads:
            t.join(timeout=30.0)
        if any(t.is_alive() for t in threads):
            print("RAFT SMOKE: FAIL — proposers wedged")
            return 2

        # convergence: every node (including the restarted one) must
        # replay every acknowledged command
        deadline = time.time() + 15.0
        missing = {}
        while time.time() < deadline:
            missing = {
                nid: acked - {c[1][0] for c in lst if c[0] == "smoke"}
                for nid, lst in applied.items()}
            if not any(missing.values()):
                break
            time.sleep(0.05)
        if any(missing.values()):
            worst = {nid: len(m) for nid, m in missing.items() if m}
            print(f"RAFT SMOKE: FAIL — acked commits missing after "
                  f"crash/restart: {worst}")
            return 2
    finally:
        for n in nodes.values():
            n.stop()
        for n in nodes.values():
            if hasattr(n.log, "close"):
                n.log.close()
        shutil.rmtree(tmp, ignore_errors=True)
    dt = time.monotonic() - t0
    print(f"RAFT SMOKE: ok — {len(acked)}/{total} acked commits survived "
          f"a leader crash/restart on all 3 nodes, {dt:.1f}s")
    return 0


def e2e_smoke(jobs_n: int = 300, nodes_n: int = 75, workers: int = 4) -> int:
    """Full-pipeline smoke (scripts/check.sh --e2e-smoke): 300 evals
    through broker -> batched workers -> pipelined plan applier -> raft
    group commit -> FSM on a durable 3-node cluster, with one leader
    crash-restart mid-stream. Asserts: zero acked (committed-in-FSM)
    allocs lost across the failover, plan rejection rate <= 5%, every
    eval drained, and the alloc-uniqueness + safety invariants hold."""
    import os
    import shutil

    from ..core.server import ServerConfig
    from ..raft.cluster import RaftCluster
    from .invariants import InvariantChecker

    t0 = time.monotonic()

    def config_fn(_i: int) -> ServerConfig:
        return ServerConfig(
            num_workers=workers, plan_commit_batching=True,
            eval_batch_size=8,
            heartbeat_ttl=3600.0, gc_interval=3600.0, nack_timeout=900.0,
            failed_eval_followup_delay=3600.0,
            failed_eval_unblock_interval=0.5)

    tmp = tempfile.mkdtemp(prefix="nomad-e2e-smoke-")
    checker = InvariantChecker()
    try:
        cluster = RaftCluster(3, config_fn=config_fn, data_dir=tmp)
        cluster.start()
        try:
            leader = cluster.wait_for_leader(timeout=15.0)
            if leader is None:
                print("E2E SMOKE: FAIL — no leader elected")
                return 2
            for _ in range(nodes_n):
                leader.register_node(mock.node())

            jobs = []
            for _ in range(jobs_n):
                j = mock.job()
                j.task_groups[0].count = 1
                # small tasks, low cluster utilization: the gate measures
                # pipeline safety across a failover, not placement
                # contention (bench.py's rungs own the contention axis)
                j.task_groups[0].tasks[0].resources.cpu = 100
                j.task_groups[0].tasks[0].resources.memory_mb = 64
                jobs.append(j)
                leader.store.upsert_job(j)
            evals = [mock.eval_for(j, create_time=time.time())
                     for j in jobs]
            leader.store.upsert_evals(evals)
            for ev in evals:
                leader.server.broker.enqueue(ev)

            # crash the leader once the pipeline is genuinely mid-batch:
            # some allocs committed, many evals still in flight
            deadline = time.time() + 60
            while time.time() < deadline:
                snap = leader.local_store.snapshot()
                committed = [a.id for a in snap.allocs()]
                if len(committed) >= jobs_n // 4:
                    break
                time.sleep(0.002)
            else:
                print("E2E SMOKE: FAIL — pipeline never reached the "
                      "crash window")
                return 2
            # everything in the crashed leader's applied FSM was
            # committed by a quorum => acked; none of it may vanish
            acked = set(committed)
            old_stats = dict(leader.server.plan_applier.stats)
            cluster.crash(leader.id)

            fresh = cluster.wait_for_leader(timeout=20.0)
            if fresh is None:
                print("E2E SMOKE: FAIL — no leader after the crash")
                return 2
            cluster.restart(leader.id)

            # drain: _restore_evals re-enqueued every still-pending
            # eval on the new leader; wait until all evals terminal
            # and nothing is parked in the blocked tracker
            deadline = time.time() + 180
            while True:
                fresh = cluster.leader() or fresh
                if fresh.server._running \
                        and fresh.server.wait_for_idle(
                            timeout=10.0, include_delayed=False) \
                        and fresh.server.blocked.blocked_count() == 0:
                    snap = fresh.local_store.snapshot()
                    placed = [a for a in snap.allocs()
                              if not a.terminal_status()
                              and not a.server_terminal()]
                    if len(placed) >= jobs_n:
                        break
                if time.time() > deadline:
                    print("E2E SMOKE: FAIL — pipeline did not drain "
                          "after the failover")
                    return 2
                time.sleep(0.1)

            checker.check_convergence(cluster, timeout=30.0)
            checker.check_all(cluster)

            snap = fresh.local_store.snapshot()
            lost = acked - {a.id for a in snap.allocs()}
            if lost:
                print(f"E2E SMOKE: FAIL — {len(lost)} acked alloc(s) "
                      f"lost across the failover: "
                      f"{sorted(i[:8] for i in lost)[:5]}")
                return 2

            # rejection across BOTH leaderships: optimistic-concurrency
            # rejects are retried by the submitter, so the rate is
            # rejected / (placed + rejected) like bench.py's rungs
            stats = dict(fresh.server.plan_applier.stats)
            rejected = (stats.get("nodes_rejected", 0)
                        + old_stats.get("nodes_rejected", 0))
            rejection = rejected / max(len(placed) + rejected, 1)
            if rejection > 0.05:
                print(f"E2E SMOKE: FAIL — plan rejection rate "
                      f"{rejection:.1%} > 5%")
                return 2
        finally:
            cluster.stop()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    dt = time.monotonic() - t0
    print(f"E2E SMOKE: ok — {jobs_n} evals, {len(acked)} allocs acked "
          f"pre-crash all survived the leader restart, "
          f"rejection {rejection:.1%}, "
          f"{checker.stats['checks']} invariant sweeps, {dt:.1f}s")
    return 0


def solve_smoke(nodes_n: int = 40, jobs_n: int = 4,
                count: int = 256) -> int:
    """Global-batch solve smoke (scripts/check.sh --solve-smoke): a
    live 3-node cluster with batched workers under "tpu-solve", jobs
    sized to engage the bulk tier (count >= tensor/placer BULK_MIN).
    Asserts: every placement lands, at least one whole worker batch
    went through the joint auction launch, the selected assignment's
    packing score is >= the in-launch greedy counterfactual (the
    portfolio guarantee, checked end to end), and the alloc-set
    uniqueness + safety invariants hold on every replica."""
    import shutil

    from ..core.server import ServerConfig
    from ..structs import enums
    from ..structs.operator import SchedulerConfiguration
    from .invariants import InvariantChecker

    t0 = time.monotonic()

    def config_fn(_i: int) -> ServerConfig:
        return ServerConfig(
            num_workers=2, eval_batch_size=4, plan_commit_batching=True,
            sched_config=SchedulerConfiguration(
                scheduler_algorithm=enums.SCHED_ALG_TPU_SOLVE),
            heartbeat_ttl=3600.0, gc_interval=3600.0, nack_timeout=900.0,
            failed_eval_followup_delay=3600.0,
            failed_eval_unblock_interval=0.5)

    tmp = tempfile.mkdtemp(prefix="nomad-solve-smoke-")
    checker = InvariantChecker()
    try:
        cluster = RaftCluster(3, config_fn=config_fn, data_dir=tmp)
        cluster.start()
        try:
            leader = cluster.wait_for_leader(timeout=15.0)
            if leader is None:
                print("SOLVE SMOKE: FAIL — no leader elected")
                return 2
            for i in range(nodes_n):
                n = mock.node()
                n.resources.cpu = 16000
                n.resources.memory_mb = 32768
                n.compute_class()
                leader.register_node(n)

            from ..tensor.solver import get_service
            svc0 = dict(get_service().stats)

            jobs = []
            for i in range(jobs_n):
                j = mock.batch_job()
                tg = j.task_groups[0]
                tg.count = count
                tg.tasks[0].resources.cpu = (50, 80, 120, 60)[i % 4]
                tg.tasks[0].resources.memory_mb = (48, 96, 64, 128)[i % 4]
                jobs.append(j)
                leader.register_job(j)

            deadline = time.time() + 240
            while True:
                if leader.server.wait_for_idle(
                        timeout=10.0, include_delayed=False) \
                        and leader.server.blocked.blocked_count() == 0:
                    break
                if time.time() > deadline:
                    print("SOLVE SMOKE: FAIL — pipeline did not drain")
                    return 2
                time.sleep(0.1)

            checker.check_convergence(cluster, timeout=30.0)
            checker.check_all(cluster)

            snap = leader.local_store.snapshot()
            placed = [a for a in snap.allocs()
                      if not a.terminal_status() and not a.server_terminal()]
            want = jobs_n * count
            if len(placed) != want:
                print(f"SOLVE SMOKE: FAIL — {len(placed)}/{want} "
                      f"placements landed")
                return 2
            ids = {a.id for a in placed}
            if len(ids) != len(placed):
                print("SOLVE SMOKE: FAIL — duplicate alloc ids")
                return 2

            svc = get_service().stats
            launches = svc["joint_launches"] - svc0.get("joint_launches", 0)
            score_s = svc["joint_score"] - svc0.get("joint_score", 0.0)
            score_g = svc["greedy_score"] - svc0.get("greedy_score", 0.0)
            if launches < 1:
                print("SOLVE SMOKE: FAIL — no batch reached the joint "
                      "auction tier (joint_launches == 0)")
                return 2
            if score_s < score_g - 1e-3:
                print(f"SOLVE SMOKE: FAIL — selected packing score "
                      f"{score_s:.3f} below the greedy counterfactual "
                      f"{score_g:.3f}")
                return 2
        finally:
            cluster.stop()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    dt = time.monotonic() - t0
    print(f"SOLVE SMOKE: ok — {want} placements via {launches} joint "
          f"launch(es), selected score {score_s:.2f} >= greedy "
          f"{score_g:.2f}, {checker.stats['checks']} invariant sweeps, "
          f"{dt:.1f}s")
    return 0


def snap_smoke(jobs_n: int = 200, nodes_n: int = 60, workers: int = 4,
               snapshot_threshold: int = 120) -> int:
    """Snapshot/compaction smoke (scripts/check.sh --snap-smoke): the
    e2e pipeline runs on a durable 3-node cluster with a snapshot
    threshold low enough that every replica snapshots + compacts under
    load. One follower is crashed and its data_dir wiped AFTER the
    leader has compacted past the wiped state, so the restart can only
    catch up via the chunked install-snapshot path — mid-traffic.
    Asserts: the wiped follower converges, zero acked-commit loss on
    every replica, alloc-set uniqueness on every replica, and the full
    invariant sweep passes."""
    import os
    import shutil

    from ..core.server import ServerConfig
    from ..raft.cluster import RaftCluster
    from .invariants import InvariantChecker

    t0 = time.monotonic()

    def config_fn(_i: int) -> ServerConfig:
        return ServerConfig(
            num_workers=workers, plan_commit_batching=True,
            eval_batch_size=8,
            heartbeat_ttl=3600.0, gc_interval=3600.0, nack_timeout=900.0,
            failed_eval_followup_delay=3600.0,
            failed_eval_unblock_interval=0.5)

    tmp = tempfile.mkdtemp(prefix="nomad-snap-smoke-")
    checker = InvariantChecker()
    try:
        cluster = RaftCluster(3, config_fn=config_fn, data_dir=tmp,
                              snapshot_threshold=snapshot_threshold)
        cluster.start()
        try:
            leader = cluster.wait_for_leader(timeout=15.0)
            if leader is None:
                print("SNAP SMOKE: FAIL — no leader elected")
                return 2
            # shrink the transfer chunk so the install is genuinely
            # multi-frame at this store size
            for s in cluster.servers.values():
                s.raft.snapshot_chunk_bytes = 64 * 1024

            for _ in range(nodes_n):
                leader.register_node(mock.node())
            jobs = []
            for _ in range(jobs_n):
                j = mock.job()
                j.task_groups[0].count = 1
                j.task_groups[0].tasks[0].resources.cpu = 100
                j.task_groups[0].tasks[0].resources.memory_mb = 64
                jobs.append(j)
                leader.store.upsert_job(j)
            evals = [mock.eval_for(j, create_time=time.time())
                     for j in jobs]
            leader.store.upsert_evals(evals)
            for ev in evals:
                leader.server.broker.enqueue(ev)

            # wipe window: some allocs committed (acked), many evals
            # still in flight, and the leader has already compacted —
            # so the wiped follower's entries are physically gone
            deadline = time.time() + 90
            while time.time() < deadline:
                snap = leader.local_store.snapshot()
                committed = [a.id for a in snap.allocs()]
                if len(committed) >= jobs_n // 4 \
                        and leader.raft.log.base_index > 0:
                    break
                time.sleep(0.002)
            else:
                print("SNAP SMOKE: FAIL — pipeline never reached the "
                      "wipe window (committed allocs + a compaction)")
                return 2
            acked = set(committed)
            leader_base = leader.raft.log.base_index

            victim_id = next(i for i, s in cluster.servers.items()
                             if s is not leader)
            old = cluster.crash(victim_id)
            shutil.rmtree(os.path.join(old.data_dir, "raft"),
                          ignore_errors=True)
            victim = cluster.restart(victim_id)

            # drain with the wiped follower racing its chunked install
            # against live plan traffic
            deadline = time.time() + 180
            while True:
                if leader.server._running \
                        and leader.server.wait_for_idle(
                            timeout=10.0, include_delayed=False) \
                        and leader.server.blocked.blocked_count() == 0:
                    snap = leader.local_store.snapshot()
                    placed = [a for a in snap.allocs()
                              if not a.terminal_status()
                              and not a.server_terminal()]
                    if len(placed) >= jobs_n:
                        break
                if time.time() > deadline:
                    print("SNAP SMOKE: FAIL — pipeline did not drain "
                          "after the follower wipe")
                    return 2
                time.sleep(0.1)

            checker.check_convergence(cluster, timeout=60.0)
            checker.check_all(cluster)

            # the wiped follower can't have replayed entries <= the
            # leader's pre-wipe base from its (empty) log: a base past
            # that point proves the chunked install delivered it
            if victim.raft.log.base_index < leader_base:
                print(f"SNAP SMOKE: FAIL — wiped follower base "
                      f"{victim.raft.log.base_index} < leader's "
                      f"pre-wipe base {leader_base}; catch-up did not "
                      f"go through install-snapshot")
                return 2
            if victim.raft.snapshots.last_index <= 0:
                print("SNAP SMOKE: FAIL — wiped follower has no "
                      "persisted snapshot after catch-up")
                return 2

            for sid, s in cluster.servers.items():
                snap = s.local_store.snapshot()
                ids = [a.id for a in snap.allocs()]
                if len(ids) != len(set(ids)):
                    print(f"SNAP SMOKE: FAIL — duplicate alloc ids on "
                          f"{sid}")
                    return 2
                lost = acked - set(ids)
                if lost:
                    print(f"SNAP SMOKE: FAIL — {len(lost)} acked "
                          f"alloc(s) missing on {sid}: "
                          f"{sorted(i[:8] for i in lost)[:5]}")
                    return 2
        finally:
            cluster.stop()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    dt = time.monotonic() - t0
    print(f"SNAP SMOKE: ok — {jobs_n} evals, {len(acked)} allocs acked "
          f"pre-wipe all present on every replica, wiped follower "
          f"caught up via chunked install (base {leader_base} -> "
          f"{victim.raft.log.base_index}), "
          f"{checker.stats['checks']} invariant sweeps, {dt:.1f}s")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="python -m nomad_tpu.chaos")
    parser.add_argument("--seed", type=int, default=None,
                        help="fault seed (default: NOMAD_TPU_CHAOS_SEED or 0)")
    parser.add_argument("--raft-smoke", action="store_true",
                        help="run the raft group-commit crash smoke "
                             "instead of the scenario smoke")
    parser.add_argument("--e2e-smoke", action="store_true",
                        help="run the full-pipeline smoke (300 evals, "
                             "3 nodes, leader restart mid-stream) "
                             "instead of the scenario smoke")
    parser.add_argument("--solve-smoke", action="store_true",
                        help="run the global-batch solve smoke "
                             "(batched workers under tpu-solve; joint "
                             "launch, score dominance, alloc "
                             "uniqueness) instead of the scenario smoke")
    parser.add_argument("--snap-smoke", action="store_true",
                        help="run the snapshot/compaction smoke (low "
                             "snapshot threshold under e2e load, one "
                             "follower wiped + restarted, catch-up via "
                             "chunked install-snapshot) instead of the "
                             "scenario smoke")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO if args.verbose else logging.WARNING,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    import os
    if args.seed is not None:
        os.environ["NOMAD_TPU_CHAOS_SEED"] = str(args.seed)
    if args.raft_smoke:
        return raft_smoke()
    if args.e2e_smoke:
        return e2e_smoke()
    if args.solve_smoke:
        return solve_smoke()
    if args.snap_smoke:
        return snap_smoke()

    t0 = time.monotonic()
    with tempfile.TemporaryDirectory(prefix="nomad-chaos-") as tmp:
        cluster = RaftCluster(3, data_dir=tmp)
        cluster.start()
        try:
            runner = build_scenario(cluster)
            try:
                report = runner.run()
            except InvariantViolation as e:
                print(f"CHAOS SMOKE: FAIL — {e} "
                      f"(reproduce: NOMAD_TPU_CHAOS_SEED={runner.seed})")
                return 2
        finally:
            cluster.stop()
    dt = time.monotonic() - t0
    print(f"CHAOS SMOKE: ok — {len(report['steps'])} steps, "
          f"seed={report['seed']}, faults={report['faults']}, "
          f"{dt:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
