"""Chaos smoke: one scripted partition + crash scenario on a durable
3-node cluster, fixed seed, well under a minute.

    python -m nomad_tpu.chaos [--seed N]
    python -m nomad_tpu.chaos --raft-smoke

Exit 0 when every invariant holds; 2 on a violation (the CI gate in
scripts/check.sh). This is the smallest end-to-end proof that the
fault layer, the recovery paths, and the invariant sweep all work —
the full scenario matrix lives in tests/test_chaos.py.

`--raft-smoke` runs the group-commit write-path smoke instead: 3
durable raft nodes, 500 commands from 8 concurrent proposers, a leader
crash-restart in the middle — asserts zero acknowledged commits lost
(PERF.md "The replicated write path")."""

from __future__ import annotations

import argparse
import logging
import sys
import tempfile
import time

from .. import mock
from ..raft.cluster import RaftCluster
from .invariants import InvariantViolation
from .runner import ScenarioRunner, seed_from_env

log = logging.getLogger("nomad_tpu.chaos")


def _live_entry(cluster):
    return next(s for s in cluster.servers.values() if not s.crashed)


def build_scenario(cluster) -> ScenarioRunner:
    r = ScenarioRunner(cluster, seed=seed_from_env())

    @r.step("elect + seed workload")
    def _seed(r):
        leader = r.wait_for_leader()
        entry = _live_entry(cluster)
        for _ in range(2):
            entry.register_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 2
        entry.register_job(job)
        leader.server.wait_for_idle(15.0)

    @r.step("cut the leader's outbound links (directed partition)")
    def _cut(r):
        leader = r.wait_for_leader()
        others = [sid for sid in cluster.servers if sid != leader.id]
        for sid in others:
            cluster.transport.partition_link(leader.id, sid)
        # followers miss heartbeats and elect among themselves; the old
        # leader still hears the higher term and steps down
        deadline = time.time() + 10
        while time.time() < deadline:
            fresh = cluster.leader()
            if fresh is not None and fresh.id != leader.id:
                return
            time.sleep(0.05)
        raise InvariantViolation("no replacement leader after directed cut")

    @r.step("write through the new leader, then heal")
    def _write_and_heal(r):
        entry = _live_entry(cluster)
        entry.register_node(mock.node())
        r.heal_and_converge()

    @r.step("crash the leader mid-write, restart, converge")
    def _crash_restart(r):
        leader = r.wait_for_leader()
        entry = next(s for s in cluster.servers.values()
                     if not s.crashed and s.id != leader.id)
        cluster.crash(leader.id)
        entry.register_node(mock.node())  # forwarded to the new leader
        cluster.restart(leader.id)
        r.heal_and_converge(timeout=20.0)

    return r


def raft_smoke(total: int = 500, proposers: int = 8) -> int:
    """Group-commit smoke: `total` commands through a 3-node durable
    cluster with a leader crash-restart in the middle. Every command
    the proposers saw acknowledged must be present on the post-crash
    leader AND replayed by the restarted node — zero lost commits."""
    import os
    import shutil
    import tempfile
    import threading

    from ..raft.durable import DurableLog
    from ..raft.node import NotLeaderError, RaftNode
    from ..raft.transport import InProcTransport

    t0 = time.monotonic()
    tmp = tempfile.mkdtemp(prefix="nomad-raft-smoke-")
    transport = InProcTransport()
    ids = ["a", "b", "c"]
    applied = {}

    def build(nid: str) -> RaftNode:
        d = os.path.join(tmp, nid)
        os.makedirs(d, exist_ok=True)
        mine = applied[nid] = []  # restart replays into a fresh list
        return RaftNode(nid, ids, transport,
                        lambda cmd, l=mine: l.append(cmd) or len(l),
                        log=DurableLog(d))

    nodes = {nid: build(nid) for nid in ids}
    for n in nodes.values():
        n.start()

    def current_leader(timeout: float = 10.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            for n in nodes.values():
                if n.is_leader():
                    return n
            time.sleep(0.01)
        return None

    try:
        if current_leader() is None:
            print("RAFT SMOKE: FAIL — no leader elected")
            return 2
        acked: set = set()
        acked_lock = threading.Lock()

        def propose(start: int) -> None:
            for i in range(start, total, proposers):
                cmd = ("smoke", (i,), {})
                # an errored apply is AMBIGUOUS (it may still commit);
                # retry until an unambiguous ack — duplicates are fine,
                # the assertion below is set inclusion
                while True:
                    leader = current_leader()
                    if leader is None:
                        time.sleep(0.02)
                        continue
                    try:
                        leader.apply(cmd, timeout=5.0)
                    except (NotLeaderError, TimeoutError):
                        time.sleep(0.01)
                        continue
                    with acked_lock:
                        acked.add(i)
                    break

        threads = [threading.Thread(target=propose, args=(i,), daemon=True)
                   for i in range(proposers)]
        for t in threads:
            t.start()

        # crash the leader mid-stream, then restart it over its data dir
        while True:
            with acked_lock:
                if len(acked) >= total // 2:
                    break
            time.sleep(0.005)
        victim = current_leader()
        if victim is not None:
            vid = victim.id
            transport.unregister(vid)
            victim.stop()
            victim.log.close()
            nodes[vid] = build(vid)
            nodes[vid].start()

        for t in threads:
            t.join(timeout=30.0)
        if any(t.is_alive() for t in threads):
            print("RAFT SMOKE: FAIL — proposers wedged")
            return 2

        # convergence: every node (including the restarted one) must
        # replay every acknowledged command
        deadline = time.time() + 15.0
        missing = {}
        while time.time() < deadline:
            missing = {
                nid: acked - {c[1][0] for c in lst if c[0] == "smoke"}
                for nid, lst in applied.items()}
            if not any(missing.values()):
                break
            time.sleep(0.05)
        if any(missing.values()):
            worst = {nid: len(m) for nid, m in missing.items() if m}
            print(f"RAFT SMOKE: FAIL — acked commits missing after "
                  f"crash/restart: {worst}")
            return 2
    finally:
        for n in nodes.values():
            n.stop()
        for n in nodes.values():
            if hasattr(n.log, "close"):
                n.log.close()
        shutil.rmtree(tmp, ignore_errors=True)
    dt = time.monotonic() - t0
    print(f"RAFT SMOKE: ok — {len(acked)}/{total} acked commits survived "
          f"a leader crash/restart on all 3 nodes, {dt:.1f}s")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="python -m nomad_tpu.chaos")
    parser.add_argument("--seed", type=int, default=None,
                        help="fault seed (default: NOMAD_TPU_CHAOS_SEED or 0)")
    parser.add_argument("--raft-smoke", action="store_true",
                        help="run the raft group-commit crash smoke "
                             "instead of the scenario smoke")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO if args.verbose else logging.WARNING,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    import os
    if args.seed is not None:
        os.environ["NOMAD_TPU_CHAOS_SEED"] = str(args.seed)
    if args.raft_smoke:
        return raft_smoke()

    t0 = time.monotonic()
    with tempfile.TemporaryDirectory(prefix="nomad-chaos-") as tmp:
        cluster = RaftCluster(3, data_dir=tmp)
        cluster.start()
        try:
            runner = build_scenario(cluster)
            try:
                report = runner.run()
            except InvariantViolation as e:
                print(f"CHAOS SMOKE: FAIL — {e} "
                      f"(reproduce: NOMAD_TPU_CHAOS_SEED={runner.seed})")
                return 2
        finally:
            cluster.stop()
    dt = time.monotonic() - t0
    print(f"CHAOS SMOKE: ok — {len(report['steps'])} steps, "
          f"seed={report['seed']}, faults={report['faults']}, "
          f"{dt:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
