"""Chaos smoke: one scripted partition + crash scenario on a durable
3-node cluster, fixed seed, well under a minute.

    python -m nomad_tpu.chaos [--seed N]
    python -m nomad_tpu.chaos --raft-smoke
    python -m nomad_tpu.chaos --e2e-smoke
    python -m nomad_tpu.chaos --solve-smoke
    python -m nomad_tpu.chaos --snap-smoke
    python -m nomad_tpu.chaos --swarm-smoke
    python -m nomad_tpu.chaos --watch-smoke
    python -m nomad_tpu.chaos --flow-smoke
    python -m nomad_tpu.chaos --load-smoke
    python -m nomad_tpu.chaos --swarm-scale [N]

Exit 0 when every invariant holds; 2 on a violation (the CI gate in
scripts/check.sh). This is the smallest end-to-end proof that the
fault layer, the recovery paths, and the invariant sweep all work —
the full scenario matrix lives in tests/test_chaos.py.

`--raft-smoke` runs the group-commit write-path smoke instead: 3
durable raft nodes, 500 commands from 8 concurrent proposers, a leader
crash-restart in the middle — asserts zero acknowledged commits lost
(PERF.md "The replicated write path").

`--e2e-smoke` runs the full-pipeline smoke: 300 evals through
broker -> batched workers -> pipelined plan applier -> raft group
commit -> FSM on a durable 3-node cluster, with one leader restart
mid-stream — zero acked allocs lost, rejection <= 5% (the
scripts/check.sh --e2e-smoke gate; PERF.md "End-to-end pipeline").

`--solve-smoke` runs the global-batch solve smoke: bulk-sized jobs
through batched workers under "tpu-solve" on a live 3-node cluster —
asserts a whole worker batch reached the joint auction launch, the
selected packing score dominates the in-launch greedy counterfactual,
and every replica holds a unique alloc set (the scripts/check.sh
--solve-smoke gate; PERF.md "Global-batch solve").

`--snap-smoke` runs the snapshot/compaction smoke: the e2e pipeline on
a durable 3-node cluster with a low snapshot threshold (every replica
snapshots + compacts under load); one follower is crashed and wiped
after the leader compacts, and the restart must catch up via the
chunked install-snapshot path mid-traffic — zero acked-commit loss and
alloc-set uniqueness on every replica (the scripts/check.sh
--snap-smoke gate; ROBUSTNESS.md "Durability at scale").

`--swarm-smoke` runs the client-plane swarm smoke: 200 sim nodes
speaking the real register/heartbeat-batch/alloc-ack surface while a
churn loop flaps a rolling slice and THREE leaders crash in sequence —
no stable node is ever wrongly expired, silenced nodes expire only
after a real >= TTL silence and recover on their next beat, and every
replica passes check_node_liveness + alloc uniqueness (the
scripts/check.sh --swarm-smoke gate; ROBUSTNESS.md "Client plane").

`--swarm-scale [N]` runs the fleet-scale acceptance smoke: N (default
50,000) sim nodes heartbeating at the production TTL against a live
3-node cluster WHILE the e2e pipeline runs, one leader crash/failover
mid-stream — zero missed-TTL false positives on any replica.

`--flow-smoke` runs the event-completeness smoke: the e2e pipeline on
a 3-node cluster with the nomadflow shadow replicas force-armed — every
server's event stream is replayed into a reduced replica and
fingerprint-compared against MVCC snapshot rebuilds across a leader
crash/restart; any mutation whose delta never reached the stream fails
the run (the scripts/check.sh --flow-smoke gate; ANALYSIS.md
"nomadflow").

`--load-smoke` runs the overload smoke: a durable 3-node cluster under
a ~10x open-loop job-submit burst (seeded Poisson arrivals that do NOT
let up when the server slows) with a leader crash mid-burst — no
heartbeat is ever shed, heartbeat p99 stays bounded, zero missed-TTL
false positives, every acked submit survives the failover, and
invariant 10 (overload tier ordering) holds on every replica (the
scripts/check.sh --load-smoke gate; ROBUSTNESS.md "Overload
envelope").

`--watch-smoke` runs the read-path failover smoke: blocking queries +
event subscriptions parked on ALL 3 servers while the leader crashes —
survivors' parked queries complete with the post-failover result at a
higher index, fresh reads on the dead server fail fast with
X-Nomad-KnownLeader=false, and the X-Nomad-LastContact stale bound
holds across the transition (the scripts/check.sh --watch-smoke gate;
PERF.md "Read path at fan-out scale")."""

from __future__ import annotations

import argparse
import logging
import os
import sys
import tempfile
import threading
import time

from .. import mock
from ..raft.cluster import RaftCluster
from .invariants import InvariantViolation
from .runner import ScenarioRunner, seed_from_env

log = logging.getLogger("nomad_tpu.chaos")


def _live_entry(cluster):
    return next(s for s in cluster.servers.values() if not s.crashed)


def build_scenario(cluster) -> ScenarioRunner:
    r = ScenarioRunner(cluster, seed=seed_from_env())

    @r.step("elect + seed workload")
    def _seed(r):
        leader = r.wait_for_leader()
        entry = _live_entry(cluster)
        for _ in range(2):
            entry.register_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 2
        entry.register_job(job)
        leader.server.wait_for_idle(15.0)

    @r.step("cut the leader's outbound links (directed partition)")
    def _cut(r):
        leader = r.wait_for_leader()
        others = [sid for sid in cluster.servers if sid != leader.id]
        for sid in others:
            cluster.transport.partition_link(leader.id, sid)
        # followers miss heartbeats and elect among themselves; the old
        # leader still hears the higher term and steps down
        deadline = time.time() + 10
        while time.time() < deadline:
            fresh = cluster.leader()
            if fresh is not None and fresh.id != leader.id:
                return
            time.sleep(0.05)
        raise InvariantViolation("no replacement leader after directed cut")

    @r.step("write through the new leader, then heal")
    def _write_and_heal(r):
        entry = _live_entry(cluster)
        entry.register_node(mock.node())
        r.heal_and_converge()

    @r.step("crash the leader mid-write, restart, converge")
    def _crash_restart(r):
        leader = r.wait_for_leader()
        entry = next(s for s in cluster.servers.values()
                     if not s.crashed and s.id != leader.id)
        cluster.crash(leader.id)
        entry.register_node(mock.node())  # forwarded to the new leader
        cluster.restart(leader.id)
        r.heal_and_converge(timeout=20.0)

    return r


def raft_smoke(total: int = 500, proposers: int = 8) -> int:
    """Group-commit smoke: `total` commands through a 3-node durable
    cluster with a leader crash-restart in the middle. Every command
    the proposers saw acknowledged must be present on the post-crash
    leader AND replayed by the restarted node — zero lost commits."""
    import os
    import shutil
    import tempfile
    import threading

    from ..raft.durable import DurableLog
    from ..raft.node import NotLeaderError, RaftNode
    from ..raft.transport import InProcTransport

    t0 = time.monotonic()
    tmp = tempfile.mkdtemp(prefix="nomad-raft-smoke-")
    transport = InProcTransport()
    ids = ["a", "b", "c"]
    applied = {}

    def build(nid: str) -> RaftNode:
        d = os.path.join(tmp, nid)
        os.makedirs(d, exist_ok=True)
        mine = applied[nid] = []  # restart replays into a fresh list
        return RaftNode(nid, ids, transport,
                        lambda cmd, l=mine: l.append(cmd) or len(l),
                        log=DurableLog(d))

    nodes = {nid: build(nid) for nid in ids}
    for n in nodes.values():
        n.start()

    def current_leader(timeout: float = 10.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            for n in nodes.values():
                if n.is_leader():
                    return n
            time.sleep(0.01)
        return None

    try:
        if current_leader() is None:
            print("RAFT SMOKE: FAIL — no leader elected")
            return 2
        acked: set = set()
        acked_lock = threading.Lock()

        def propose(start: int) -> None:
            for i in range(start, total, proposers):
                cmd = ("smoke", (i,), {})
                # an errored apply is AMBIGUOUS (it may still commit);
                # retry until an unambiguous ack — duplicates are fine,
                # the assertion below is set inclusion
                while True:
                    leader = current_leader()
                    if leader is None:
                        time.sleep(0.02)
                        continue
                    try:
                        leader.apply(cmd, timeout=5.0)
                    except (NotLeaderError, TimeoutError):
                        time.sleep(0.01)
                        continue
                    with acked_lock:
                        acked.add(i)
                    break

        threads = [threading.Thread(target=propose, args=(i,), daemon=True)
                   for i in range(proposers)]
        for t in threads:
            t.start()

        # crash the leader mid-stream, then restart it over its data dir
        while True:
            with acked_lock:
                if len(acked) >= total // 2:
                    break
            time.sleep(0.005)
        victim = current_leader()
        if victim is not None:
            vid = victim.id
            transport.unregister(vid)
            victim.stop()
            victim.log.close()
            nodes[vid] = build(vid)
            nodes[vid].start()

        for t in threads:
            t.join(timeout=30.0)
        if any(t.is_alive() for t in threads):
            print("RAFT SMOKE: FAIL — proposers wedged")
            return 2

        # convergence: every node (including the restarted one) must
        # replay every acknowledged command
        deadline = time.time() + 15.0
        missing = {}
        while time.time() < deadline:
            missing = {
                nid: acked - {c[1][0] for c in lst if c[0] == "smoke"}
                for nid, lst in applied.items()}
            if not any(missing.values()):
                break
            time.sleep(0.05)
        if any(missing.values()):
            worst = {nid: len(m) for nid, m in missing.items() if m}
            print(f"RAFT SMOKE: FAIL — acked commits missing after "
                  f"crash/restart: {worst}")
            return 2
    finally:
        for n in nodes.values():
            n.stop()
        for n in nodes.values():
            if hasattr(n.log, "close"):
                n.log.close()
        shutil.rmtree(tmp, ignore_errors=True)
    dt = time.monotonic() - t0
    print(f"RAFT SMOKE: ok — {len(acked)}/{total} acked commits survived "
          f"a leader crash/restart on all 3 nodes, {dt:.1f}s")
    return 0


def e2e_smoke(jobs_n: int = 300, nodes_n: int = 75, workers: int = 4) -> int:
    """Full-pipeline smoke (scripts/check.sh --e2e-smoke): 300 evals
    through broker -> batched workers -> pipelined plan applier -> raft
    group commit -> FSM on a durable 3-node cluster, with one leader
    crash-restart mid-stream. Asserts: zero acked (committed-in-FSM)
    allocs lost across the failover, plan rejection rate <= 5%, every
    eval drained, and the alloc-uniqueness + safety invariants hold."""
    import os
    import shutil

    from ..core.server import ServerConfig
    from ..raft.cluster import RaftCluster
    from .invariants import InvariantChecker

    t0 = time.monotonic()

    def config_fn(_i: int) -> ServerConfig:
        return ServerConfig(
            num_workers=workers, plan_commit_batching=True,
            eval_batch_size=8,
            heartbeat_ttl=3600.0, gc_interval=3600.0, nack_timeout=900.0,
            failed_eval_followup_delay=3600.0,
            failed_eval_unblock_interval=0.5)

    tmp = tempfile.mkdtemp(prefix="nomad-e2e-smoke-")
    checker = InvariantChecker()
    try:
        cluster = RaftCluster(3, config_fn=config_fn, data_dir=tmp)
        cluster.start()
        try:
            leader = cluster.wait_for_leader(timeout=15.0)
            if leader is None:
                print("E2E SMOKE: FAIL — no leader elected")
                return 2
            for _ in range(nodes_n):
                leader.register_node(mock.node())

            jobs = []
            for _ in range(jobs_n):
                j = mock.job()
                j.task_groups[0].count = 1
                # small tasks, low cluster utilization: the gate measures
                # pipeline safety across a failover, not placement
                # contention (bench.py's rungs own the contention axis)
                j.task_groups[0].tasks[0].resources.cpu = 100
                j.task_groups[0].tasks[0].resources.memory_mb = 64
                jobs.append(j)
                leader.store.upsert_job(j)
            evals = [mock.eval_for(j, create_time=time.time())
                     for j in jobs]
            leader.store.upsert_evals(evals)
            for ev in evals:
                leader.server.broker.enqueue(ev)

            # crash the leader once the pipeline is genuinely mid-batch:
            # some allocs committed, many evals still in flight
            deadline = time.time() + 60
            while time.time() < deadline:
                snap = leader.local_store.snapshot()
                committed = [a.id for a in snap.allocs()]
                if len(committed) >= jobs_n // 4:
                    break
                time.sleep(0.002)
            else:
                print("E2E SMOKE: FAIL — pipeline never reached the "
                      "crash window")
                return 2
            # everything in the crashed leader's applied FSM was
            # committed by a quorum => acked; none of it may vanish
            acked = set(committed)
            old_stats = dict(leader.server.plan_applier.stats)
            cluster.crash(leader.id)

            fresh = cluster.wait_for_leader(timeout=20.0)
            if fresh is None:
                print("E2E SMOKE: FAIL — no leader after the crash")
                return 2
            cluster.restart(leader.id)

            # drain: _restore_evals re-enqueued every still-pending
            # eval on the new leader; wait until all evals terminal
            # and nothing is parked in the blocked tracker
            deadline = time.time() + 180
            while True:
                fresh = cluster.leader() or fresh
                if fresh.server._running \
                        and fresh.server.wait_for_idle(
                            timeout=10.0, include_delayed=False) \
                        and fresh.server.blocked.blocked_count() == 0:
                    snap = fresh.local_store.snapshot()
                    placed = [a for a in snap.allocs()
                              if not a.terminal_status()
                              and not a.server_terminal()]
                    if len(placed) >= jobs_n:
                        break
                if time.time() > deadline:
                    print("E2E SMOKE: FAIL — pipeline did not drain "
                          "after the failover")
                    return 2
                time.sleep(0.1)

            checker.check_convergence(cluster, timeout=30.0)
            checker.check_all(cluster)

            snap = fresh.local_store.snapshot()
            lost = acked - {a.id for a in snap.allocs()}
            if lost:
                print(f"E2E SMOKE: FAIL — {len(lost)} acked alloc(s) "
                      f"lost across the failover: "
                      f"{sorted(i[:8] for i in lost)[:5]}")
                return 2

            # rejection across BOTH leaderships: optimistic-concurrency
            # rejects are retried by the submitter, so the rate is
            # rejected / (placed + rejected) like bench.py's rungs
            stats = dict(fresh.server.plan_applier.stats)
            rejected = (stats.get("nodes_rejected", 0)
                        + old_stats.get("nodes_rejected", 0))
            rejection = rejected / max(len(placed) + rejected, 1)
            if rejection > 0.05:
                print(f"E2E SMOKE: FAIL — plan rejection rate "
                      f"{rejection:.1%} > 5%")
                return 2
        finally:
            cluster.stop()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    dt = time.monotonic() - t0
    print(f"E2E SMOKE: ok — {jobs_n} evals, {len(acked)} allocs acked "
          f"pre-crash all survived the leader restart, "
          f"rejection {rejection:.1%}, "
          f"{checker.stats['checks']} invariant sweeps, {dt:.1f}s")
    return 0


def load_smoke(nodes_n: int = 30, burst_s: float = 6.0,
               workers: int = 24) -> int:
    """Overload smoke (scripts/check.sh --load-smoke): a durable
    3-node cluster under a ~10x open-loop job-submit burst with a
    leader crash mid-burst (nomadload, ROBUSTNESS.md "Overload
    envelope"). Asserts:

    - tier-0 SLO: no heartbeat was ever shed, heartbeat p99 stayed
      bounded through the burst, and zero missed-TTL false positives
      (check_node_liveness attribution on every replica);
    - the admission plane engaged (submit sheds > 0 at 10x) AND let
      real work through (ok > 0);
    - zero acked-work loss: every register_job that RETURNED is in the
      FSM after the failover drains — a shed request was refused
      before any state changed, an acked one is quorum-durable;
    - invariant 10 (overload tier ordering) + the safety sweep on
      every replica."""
    import shutil

    from ..core.loadctl import RetryLater
    from ..core.server import ServerConfig
    from ..raft.cluster import RaftCluster
    from .invariants import InvariantChecker
    from .overload import run_open_loop

    t0 = time.monotonic()

    def config_fn(_i: int) -> ServerConfig:
        return ServerConfig(
            num_workers=2, plan_commit_batching=True, eval_batch_size=8,
            heartbeat_ttl=10.0, gc_interval=3600.0, nack_timeout=900.0,
            failed_eval_followup_delay=3600.0,
            # the plane under test: force-enabled (the smoke is
            # meaningless against the kill-switch baseline) with
            # watermarks low enough that a 10x burst genuinely trips
            # them on a laptop-scale cluster. They must sit BELOW the
            # open-loop worker pool: submits block in propose, so queue
            # depth is bounded by the number of in-flight clients — a
            # soft mark above that can never be reached.
            loadctl_enabled=True,
            loadctl_proposal_soft=8, loadctl_proposal_hard=24,
            loadctl_plan_soft=8, loadctl_plan_hard=24,
            loadctl_broker_soft=16, loadctl_broker_hard=48,
            loadctl_brownout_after=0.5)

    tmp = tempfile.mkdtemp(prefix="nomad-load-smoke-")
    checker = InvariantChecker()
    failures: list = []
    try:
        # high threshold: the burst commits ~10k entries, and default
        # compaction would route the restarted victim's recovery
        # through a chunked snapshot transfer that dominates the
        # convergence budget. The transfer has its own dedicated smoke
        # (--snap-smoke); this one audits the admission plane, so
        # recovery stays on the plain append path.
        cluster = RaftCluster(3, config_fn=config_fn, data_dir=tmp,
                              snapshot_threshold=1 << 17)
        cluster.start()
        try:
            leader = cluster.wait_for_leader(timeout=15.0)
            if leader is None:
                print("LOAD SMOKE: FAIL — no leader elected")
                return 2
            nodes = [mock.node() for _ in range(nodes_n)]
            for n in nodes:
                leader.register_node(n)

            lock = threading.Lock()
            acked_jobs: list = []

            def submit(i: int) -> None:
                j = mock.job()
                j.task_groups[0].count = 1
                j.task_groups[0].tasks[0].resources.cpu = 100
                j.task_groups[0].tasks[0].resources.memory_mb = 64
                entry = cluster.leader() or _live_entry(cluster)
                entry.register_job(j)
                with lock:
                    acked_jobs.append(j.id)

            # calibrate: closed-loop sequential submits for ~1 s give
            # the max-sustainable single-client rate; the burst offers
            # 10x that, open loop
            cal_t0 = time.monotonic()
            cal_n = 0
            while time.monotonic() - cal_t0 < 1.0:
                submit(-1)
                cal_n += 1
            base_rate = cal_n / (time.monotonic() - cal_t0)
            # cap the offered rate: the smoke proves shedding + SLOs,
            # not raw throughput, and the restarted victim must replay
            # whatever the burst committed inside the smoke budget
            burst_rate = min(500.0, max(100.0, 10.0 * base_rate))

            # tier-0 plane: heartbeats keep flowing through the burst;
            # a RetryLater here fails the smoke outright
            hb_stop = threading.Event()
            hb_lat: list = []
            hb_shed = [0]
            hb_err = [0]

            def heartbeats():
                k = 0
                while not hb_stop.is_set():
                    n = nodes[k % len(nodes)]
                    k += 1
                    h0 = time.monotonic()
                    try:
                        (cluster.leader()
                         or _live_entry(cluster)).heartbeat(n.id)
                    except RetryLater:
                        with lock:
                            hb_shed[0] += 1
                    except Exception:
                        # failover window: forwarding errors are
                        # liveness noise, not sheds
                        with lock:
                            hb_err[0] += 1
                    else:
                        with lock:
                            hb_lat.append(time.monotonic() - h0)
                    hb_stop.wait(0.1)

            hb_thread = threading.Thread(target=heartbeats, daemon=True)
            hb_thread.start()
            time.sleep(1.0)  # unloaded heartbeat baseline
            with lock:
                base_hb = sorted(hb_lat)
                base_p99 = base_hb[int(0.99 * (len(base_hb) - 1))] \
                    if base_hb else 0.05
                hb_lat.clear()

            victim = (cluster.leader() or leader).id

            def crash_mid_burst():
                time.sleep(burst_s / 2)
                cluster.crash(victim)

            crasher = threading.Thread(target=crash_mid_burst,
                                       daemon=True)
            crasher.start()
            res = run_open_loop(submit, rate=burst_rate,
                                duration=burst_s,
                                seed=seed_from_env(), workers=workers)
            crasher.join(timeout=burst_s + 10.0)

            fresh = cluster.wait_for_leader(timeout=20.0)
            if fresh is None:
                print("LOAD SMOKE: FAIL — no leader after the crash")
                return 2
            cluster.restart(victim)
            # let the admitted backlog drain before auditing the FSM
            deadline = time.time() + 120
            while time.time() < deadline:
                fresh = cluster.leader() or fresh
                if fresh.server._running and fresh.server.wait_for_idle(
                        timeout=10.0, include_delayed=False):
                    break
                time.sleep(0.1)
            hb_stop.set()
            hb_thread.join(timeout=10.0)

            with lock:
                burst_hb = sorted(hb_lat)
                burst_p99 = burst_hb[int(0.99 * (len(burst_hb) - 1))] \
                    if burst_hb else 0.0

            # -- assertions --
            if hb_shed[0]:
                failures.append(
                    f"tier-0 SLO: {hb_shed[0]} heartbeat(s) shed")
            # absolute floor: under full CPU saturation the tail is
            # GIL hand-off, not queueing the plane controls. With the
            # nomadown sanitizer armed every FSM write also pays the
            # fingerprint sweep, so the floor doubles — still 5x
            # inside the 10 s heartbeat TTL.
            hb_floor = 2.0 if os.environ.get("NOMAD_TPU_SAN") == "1" \
                else 1.0
            if burst_p99 > max(10.0 * base_p99, hb_floor):
                failures.append(
                    f"tier-0 SLO: heartbeat p99 {burst_p99 * 1e3:.0f}ms "
                    f"under burst vs {base_p99 * 1e3:.0f}ms unloaded")
            if res["ok"] == 0:
                failures.append("no submit was admitted during the burst")
            if res["shed"] == 0:
                failures.append(
                    f"admission plane never engaged at 10x "
                    f"(rate {burst_rate:.0f}/s, {res})")
            snap = fresh.local_store.snapshot()
            have = {j.id for j in snap.jobs()}
            lost = [j for j in acked_jobs if j not in have]
            if lost:
                failures.append(
                    f"{len(lost)} acked job(s) lost across the "
                    f"failover: {[i[:8] for i in lost[:5]]}")
            checker.check_convergence(cluster, timeout=90.0)
            checker.check_node_liveness(cluster)
            checker.check_all(cluster)  # includes overload ordering

            if failures:
                print("LOAD SMOKE: FAIL —")
                for f in failures[:20]:
                    print(f"  {f}")
                return 2
        finally:
            cluster.stop()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    dt = time.monotonic() - t0
    print(f"LOAD SMOKE: ok — {res['offered']} offered at "
          f"{burst_rate:.0f}/s (10x of {base_rate:.0f}/s), "
          f"{res['ok']} admitted / {res['shed']} shed / "
          f"{res['errors']} errors across a leader crash, "
          f"{len(acked_jobs)} acked jobs all survived, heartbeat p99 "
          f"{burst_p99 * 1e3:.0f}ms (unloaded {base_p99 * 1e3:.0f}ms), "
          f"0 tier-0 sheds, {checker.stats['checks']} invariant "
          f"sweeps, {dt:.1f}s")
    return 0


def flow_smoke(jobs_n: int = 120, nodes_n: int = 40,
               workers: int = 4) -> int:
    """Event-completeness smoke (scripts/check.sh --flow-smoke): the
    e2e pipeline on a durable 3-node cluster with the nomadflow shadow
    tracker force-armed, so every server construction auto-attaches a
    shadow replica that replays the Allocation/Node/Evaluation stream
    and fingerprint-compares against MVCC snapshot rebuilds. One leader
    crash/restart mid-stream (the restarted server resyncs through the
    restore-truncation path). Asserts: zero shadow divergences on ANY
    replica — including the crashed one's final pre-crash state — plus
    the standard safety sweep (which now includes invariant
    check_event_completeness)."""
    import shutil

    from ..analysis import shadow
    from ..core.server import ServerConfig
    from ..raft.cluster import RaftCluster
    from .invariants import InvariantChecker

    t0 = time.monotonic()

    def config_fn(_i: int) -> ServerConfig:
        return ServerConfig(
            num_workers=workers, plan_commit_batching=True,
            eval_batch_size=8,
            heartbeat_ttl=3600.0, gc_interval=3600.0, nack_timeout=900.0,
            failed_eval_followup_delay=3600.0,
            failed_eval_unblock_interval=0.5)

    tmp = tempfile.mkdtemp(prefix="nomad-flow-smoke-")
    checker = InvariantChecker()
    was_active = shadow.GLOBAL.active
    shadow.install()   # arm BEFORE any server constructs its broker
    try:
        cluster = RaftCluster(3, config_fn=config_fn, data_dir=tmp)
        cluster.start()
        try:
            leader = cluster.wait_for_leader(timeout=15.0)
            if leader is None:
                print("FLOW SMOKE: FAIL — no leader elected")
                return 2
            for _ in range(nodes_n):
                leader.register_node(mock.node())
            jobs = []
            for _ in range(jobs_n):
                j = mock.job()
                j.task_groups[0].count = 1
                j.task_groups[0].tasks[0].resources.cpu = 100
                j.task_groups[0].tasks[0].resources.memory_mb = 64
                jobs.append(j)
                leader.store.upsert_job(j)
            evals = [mock.eval_for(j, create_time=time.time())
                     for j in jobs]
            leader.store.upsert_evals(evals)
            for ev in evals:
                leader.server.broker.enqueue(ev)

            # crash once genuinely mid-batch, same shape as e2e_smoke
            deadline = time.time() + 60
            while time.time() < deadline:
                snap = leader.local_store.snapshot()
                if len([a.id for a in snap.allocs()]) >= jobs_n // 4:
                    break
                time.sleep(0.002)
            else:
                print("FLOW SMOKE: FAIL — pipeline never reached the "
                      "crash window")
                return 2
            cluster.crash(leader.id)
            fresh = cluster.wait_for_leader(timeout=20.0)
            if fresh is None:
                print("FLOW SMOKE: FAIL — no leader after the crash")
                return 2
            cluster.restart(leader.id)

            deadline = time.time() + 180
            while True:
                fresh = cluster.leader() or fresh
                if fresh.server._running \
                        and fresh.server.wait_for_idle(
                            timeout=10.0, include_delayed=False) \
                        and fresh.server.blocked.blocked_count() == 0:
                    snap = fresh.local_store.snapshot()
                    placed = [a for a in snap.allocs()
                              if not a.terminal_status()
                              and not a.server_terminal()]
                    if len(placed) >= jobs_n:
                        break
                if time.time() > deadline:
                    print("FLOW SMOKE: FAIL — pipeline did not drain "
                          "after the failover")
                    return 2
                time.sleep(0.1)

            checker.check_convergence(cluster, timeout=30.0)
            checker.check_all(cluster)   # includes event completeness

            problems = shadow.GLOBAL.verify_all()
            stats = shadow.GLOBAL.stats()
            if problems:
                print(f"FLOW SMOKE: FAIL — {len(problems)} shadow "
                      f"divergence(s): {problems[0]}")
                return 2
            if stats["replicas"] < 4:   # 3 initial + the restart
                print(f"FLOW SMOKE: FAIL — only {stats['replicas']} "
                      f"shadow replicas attached; the server hook is "
                      f"not arming")
                return 2
            if stats["resyncs"] < stats["replicas"]:
                print("FLOW SMOKE: FAIL — a replica never took its "
                      "initial resync")
                return 2
        finally:
            cluster.stop()
    finally:
        if not was_active:
            shadow.uninstall()
        shadow.GLOBAL.replicas.clear()
        shutil.rmtree(tmp, ignore_errors=True)
    dt = time.monotonic() - t0
    print(f"FLOW SMOKE: ok — {jobs_n} evals across a leader restart, "
          f"{stats['replicas']} shadow replicas, {stats['commits']} "
          f"commits replayed, {stats['compares']} fingerprint compares, "
          f"{stats['resyncs']} resyncs, 0 divergences, "
          f"{checker.stats['checks']} invariant sweeps, {dt:.1f}s")
    return 0


def state_smoke(jobs_n: int = 120, nodes_n: int = 40,
                workers: int = 4) -> int:
    """Incremental-state smoke (scripts/check.sh --state-smoke): the
    e2e pipeline on a durable 3-node cluster with the nomadstate parity
    digests force-armed, so every tensor build the leader's workers run
    rides the device-resident O(Δ) base (tensor/incremental.py) and is
    periodically fingerprint-compared against gen-bounded snapshot
    rebuilds. One leader crash/restart mid-stream, then a forced
    event-ring truncation on the live leader followed by another
    scheduling round (the feed must take the resync path, never patch
    across the gap). Asserts: zero parity divergences on ANY feed —
    followers included (their epochs build from snapshot at verify
    time) — warm builds actually served off the fed base, and the
    truncation actually forced a resync."""
    import shutil

    from ..core.server import ServerConfig
    from ..raft.cluster import RaftCluster
    from ..structs import enums
    from ..structs.operator import SchedulerConfiguration
    from ..tensor import incremental
    from .invariants import InvariantChecker

    t0 = time.monotonic()

    def config_fn(_i: int) -> ServerConfig:
        return ServerConfig(
            num_workers=workers, plan_commit_batching=True,
            eval_batch_size=8,
            # the tensor path is the whole point: every build must route
            # through ClusterTensors (and so the incremental feed)
            sched_config=SchedulerConfiguration(
                scheduler_algorithm=enums.SCHED_ALG_TPU_BINPACK),
            heartbeat_ttl=3600.0, gc_interval=3600.0, nack_timeout=900.0,
            failed_eval_followup_delay=3600.0,
            failed_eval_unblock_interval=0.5)

    def submit_round(node, n: int) -> None:
        jobs = []
        for _ in range(n):
            j = mock.job()
            j.task_groups[0].count = 1
            j.task_groups[0].tasks[0].resources.cpu = 100
            j.task_groups[0].tasks[0].resources.memory_mb = 64
            jobs.append(j)
            node.store.upsert_job(j)
        evals = [mock.eval_for(j, create_time=time.time()) for j in jobs]
        node.store.upsert_evals(evals)
        for ev in evals:
            node.server.broker.enqueue(ev)

    def wait_placed(cluster, fallback, want: int, timeout: float):
        deadline = time.time() + timeout
        fresh = fallback
        while True:
            fresh = cluster.leader() or fresh
            if fresh.server._running \
                    and fresh.server.wait_for_idle(
                        timeout=10.0, include_delayed=False) \
                    and fresh.server.blocked.blocked_count() == 0:
                snap = fresh.local_store.snapshot()
                placed = [a for a in snap.allocs()
                          if not a.terminal_status()
                          and not a.server_terminal()]
                if len(placed) >= want:
                    return fresh
            if time.time() > deadline:
                return None
            time.sleep(0.1)

    tmp = tempfile.mkdtemp(prefix="nomad-state-smoke-")
    checker = InvariantChecker()
    was_armed = incremental.GLOBAL.san_active
    incremental.install()   # arm the parity digests BEFORE any server
    try:
        cluster = RaftCluster(3, config_fn=config_fn, data_dir=tmp)
        cluster.start()
        try:
            leader = cluster.wait_for_leader(timeout=15.0)
            if leader is None:
                print("STATE SMOKE: FAIL — no leader elected")
                return 2
            for _ in range(nodes_n):
                leader.register_node(mock.node())
            submit_round(leader, jobs_n)

            # crash once genuinely mid-batch, same shape as flow_smoke
            deadline = time.time() + 60
            while time.time() < deadline:
                snap = leader.local_store.snapshot()
                if len([a.id for a in snap.allocs()]) >= jobs_n // 4:
                    break
                time.sleep(0.002)
            else:
                print("STATE SMOKE: FAIL — pipeline never reached the "
                      "crash window")
                return 2
            cluster.crash(leader.id)
            fresh = cluster.wait_for_leader(timeout=20.0)
            if fresh is None:
                print("STATE SMOKE: FAIL — no leader after the crash")
                return 2
            cluster.restart(leader.id)

            fresh = wait_placed(cluster, fresh, jobs_n, timeout=180.0)
            if fresh is None:
                print("STATE SMOKE: FAIL — pipeline did not drain "
                      "after the failover")
                return 2

            # force the gap contract: lap every subscription on the
            # live leader's broker, then schedule another round — the
            # feed must resync from snapshot, never patch across it
            resyncs_before = incremental.GLOBAL.stats()["resyncs"]
            fresh.server.events._truncate_all()
            submit_round(fresh, jobs_n // 4)
            fresh = wait_placed(cluster, fresh, jobs_n + jobs_n // 4,
                                timeout=120.0)
            if fresh is None:
                print("STATE SMOKE: FAIL — pipeline did not drain "
                      "after the forced truncation")
                return 2

            checker.check_convergence(cluster, timeout=30.0)
            checker.check_all(cluster)   # includes state parity (11)

            problems = incremental.GLOBAL.verify_all()
            stats = incremental.GLOBAL.stats()
            if problems:
                print(f"STATE SMOKE: FAIL — {len(problems)} parity "
                      f"divergence(s): {problems[0]}")
                return 2
            if stats["feeds"] < 4:      # 3 initial + the restart
                print(f"STATE SMOKE: FAIL — only {stats['feeds']} "
                      f"feeds attached; the server hook is not arming")
                return 2
            if stats["fast_hits"] == 0 or stats["deltas_applied"] == 0:
                print(f"STATE SMOKE: FAIL — no build ever rode the "
                      f"incremental base (fast_hits="
                      f"{stats['fast_hits']}, deltas_applied="
                      f"{stats['deltas_applied']}); the O(Δ) path is "
                      f"not engaging")
                return 2
            if stats["resyncs"] <= resyncs_before:
                print("STATE SMOKE: FAIL — the forced ring truncation "
                      "never drove a feed resync")
                return 2
            if stats["parity_checks"] == 0:
                print("STATE SMOKE: FAIL — no parity digest ever ran")
                return 2
        finally:
            cluster.stop()
    finally:
        if not was_armed:
            incremental.uninstall()
        incremental.GLOBAL.feeds.clear()
        shutil.rmtree(tmp, ignore_errors=True)
    dt = time.monotonic() - t0
    print(f"STATE SMOKE: ok — {jobs_n + jobs_n // 4} evals across a "
          f"leader restart + forced truncation, {stats['feeds']} feeds, "
          f"{stats['builds']} builds ({stats['fast_hits']} off the fed "
          f"base), {stats['deltas_applied']} deltas applied, "
          f"{stats['resyncs']} resyncs, {stats['parity_checks']} parity "
          f"digests, 0 divergences, {checker.stats['checks']} invariant "
          f"sweeps, {dt:.1f}s")
    return 0


def solve_smoke(nodes_n: int = 40, jobs_n: int = 4,
                count: int = 256) -> int:
    """Global-batch solve smoke (scripts/check.sh --solve-smoke): a
    live 3-node cluster with batched workers under "tpu-solve", jobs
    sized to engage the bulk tier (count >= tensor/placer BULK_MIN).
    Asserts: every placement lands, at least one whole worker batch
    went through the joint auction launch, the selected assignment's
    packing score is >= the in-launch greedy counterfactual (the
    portfolio guarantee, checked end to end), and the alloc-set
    uniqueness + safety invariants hold on every replica.

    A second leg exercises in-kernel preemption end to end: a
    low-priority filler eats the head room, then a high-priority batch
    job must preempt its way on. Asserts the whole preemption wave
    resolved through kernels.preempt_solve (host_preempted delta == 0,
    kernel_preempted > 0) and re-runs the full invariant sweep (alloc
    uniqueness on every replica) over the post-eviction state."""
    import shutil

    from ..core.server import ServerConfig
    from ..structs import enums
    from ..structs.operator import PreemptionConfig, SchedulerConfiguration
    from .invariants import InvariantChecker

    t0 = time.monotonic()

    def config_fn(_i: int) -> ServerConfig:
        return ServerConfig(
            num_workers=2, eval_batch_size=4, plan_commit_batching=True,
            sched_config=SchedulerConfiguration(
                scheduler_algorithm=enums.SCHED_ALG_TPU_SOLVE,
                preemption_config=PreemptionConfig(
                    batch_scheduler_enabled=True,
                    service_scheduler_enabled=True)),
            heartbeat_ttl=3600.0, gc_interval=3600.0, nack_timeout=900.0,
            failed_eval_followup_delay=3600.0,
            failed_eval_unblock_interval=0.5)

    tmp = tempfile.mkdtemp(prefix="nomad-solve-smoke-")
    checker = InvariantChecker()
    try:
        cluster = RaftCluster(3, config_fn=config_fn, data_dir=tmp)
        cluster.start()
        try:
            leader = cluster.wait_for_leader(timeout=15.0)
            if leader is None:
                print("SOLVE SMOKE: FAIL — no leader elected")
                return 2
            for i in range(nodes_n):
                n = mock.node()
                n.resources.cpu = 16000
                n.resources.memory_mb = 32768
                n.compute_class()
                leader.register_node(n)

            from ..tensor.solver import get_service
            svc0 = dict(get_service().stats)

            jobs = []
            for i in range(jobs_n):
                j = mock.batch_job()
                tg = j.task_groups[0]
                tg.count = count
                tg.tasks[0].resources.cpu = (50, 80, 120, 60)[i % 4]
                tg.tasks[0].resources.memory_mb = (48, 96, 64, 128)[i % 4]
                jobs.append(j)
                leader.register_job(j)

            deadline = time.time() + 240
            while True:
                if leader.server.wait_for_idle(
                        timeout=10.0, include_delayed=False) \
                        and leader.server.blocked.blocked_count() == 0:
                    break
                if time.time() > deadline:
                    print("SOLVE SMOKE: FAIL — pipeline did not drain")
                    return 2
                time.sleep(0.1)

            checker.check_convergence(cluster, timeout=30.0)
            checker.check_all(cluster)

            snap = leader.local_store.snapshot()
            placed = [a for a in snap.allocs()
                      if not a.terminal_status() and not a.server_terminal()]
            want = jobs_n * count
            if len(placed) != want:
                print(f"SOLVE SMOKE: FAIL — {len(placed)}/{want} "
                      f"placements landed")
                return 2
            ids = {a.id for a in placed}
            if len(ids) != len(placed):
                print("SOLVE SMOKE: FAIL — duplicate alloc ids")
                return 2

            svc = get_service().stats
            launches = svc["joint_launches"] - svc0.get("joint_launches", 0)
            score_s = svc["joint_score"] - svc0.get("joint_score", 0.0)
            score_g = svc["greedy_score"] - svc0.get("greedy_score", 0.0)
            if launches < 1:
                print("SOLVE SMOKE: FAIL — no batch reached the joint "
                      "auction tier (joint_launches == 0)")
                return 2
            if score_s < score_g - 1e-3:
                print(f"SOLVE SMOKE: FAIL — selected packing score "
                      f"{score_s:.3f} below the greedy counterfactual "
                      f"{score_g:.3f}")
                return 2

            # -- preemption leg: filler (prio 20) eats the head room,
            # then a high-priority batch job preempts its way on. Every
            # row must resolve through the kernel's victim columns —
            # the exact host scanner staying cold IS the assertion.
            from ..tensor.placer import preempt_stats
            pstats0 = preempt_stats()

            def drain(label: str) -> bool:
                deadline = time.time() + 240
                while True:
                    if leader.server.wait_for_idle(
                            timeout=10.0, include_delayed=False) \
                            and leader.server.blocked.blocked_count() == 0:
                        return True
                    if time.time() > deadline:
                        print(f"SOLVE SMOKE: FAIL — {label} did not "
                              f"drain")
                        return False
                    time.sleep(0.1)

            filler = mock.batch_job()
            filler.priority = 20
            ftg = filler.task_groups[0]
            ftg.count = nodes_n
            ftg.tasks[0].resources.cpu = 8000
            ftg.tasks[0].resources.memory_mb = 13000
            leader.register_job(filler)
            if not drain("preemption filler"):
                return 2
            hi = mock.batch_job()
            hi.priority = 80
            htg = hi.task_groups[0]
            htg.count = count
            htg.tasks[0].resources.cpu = 1500
            htg.tasks[0].resources.memory_mb = 2000
            leader.register_job(hi)
            if not drain("preemption wave"):
                return 2

            pdelta = {key: val - pstats0[key]
                      for key, val in preempt_stats().items()}
            kpre, hpre = (pdelta["kernel_preempted"],
                          pdelta["host_preempted"])
            if kpre < 1:
                print("SOLVE SMOKE: FAIL — the preemption wave never "
                      "reached the kernel (kernel_preempted == 0)")
                return 2
            if hpre != 0:
                print(f"SOLVE SMOKE: FAIL — {hpre} preemption(s) "
                      f"routed through the exact host scanner on the "
                      f"bulk path (expected 0)")
                return 2
            snap = leader.local_store.snapshot()
            hi_placed = [a for a in snap.allocs_by_job(hi.id)
                         if not a.terminal_status()
                         and not a.server_terminal()]
            if len(hi_placed) != count:
                print(f"SOLVE SMOKE: FAIL — {len(hi_placed)}/{count} "
                      f"high-priority placements landed")
                return 2
            # post-eviction state: uniqueness + safety on every replica
            checker.check_convergence(cluster, timeout=30.0)
            checker.check_all(cluster)
        finally:
            cluster.stop()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    dt = time.monotonic() - t0
    print(f"SOLVE SMOKE: ok — {want} placements via {launches} joint "
          f"launch(es), selected score {score_s:.2f} >= greedy "
          f"{score_g:.2f}, preemption wave {len(hi_placed)} placements "
          f"({kpre} in-kernel, {hpre} host), "
          f"{checker.stats['checks']} invariant sweeps, {dt:.1f}s")
    return 0


def mesh_smoke(nodes_n: int = 40, jobs_n: int = 4,
               count: int = 256) -> int:
    """Multi-chip C2M smoke (scripts/check.sh --mesh-smoke): the live
    3-node cluster pipeline with the solver service running on the
    8-virtual-device mesh (check.sh exports
    XLA_FLAGS=--xla_force_host_platform_device_count=8 before jax
    imports). Batched workers under "tpu-solve" drive node-sharded
    joint launches end to end; asserts every placement lands, the
    sharded engine actually engaged (sharded launches > 0 at
    mesh_devices == 8, with live all-gather accounting and ZERO warm
    retraces), and the alloc-set uniqueness + safety invariants hold
    on every replica."""
    import os
    import shutil

    import jax

    from ..core.server import ServerConfig
    from ..structs import enums
    from ..structs.operator import SchedulerConfiguration
    from .invariants import InvariantChecker

    t0 = time.monotonic()
    if len(jax.devices()) < 2:
        print("MESH SMOKE: FAIL — single-device jax backend; export "
              "XLA_FLAGS=--xla_force_host_platform_device_count=8 "
              "before launching (scripts/check.sh --mesh-smoke does)")
        return 2
    os.environ["NOMAD_TPU_MESH_DEVICES"] = "8"

    def config_fn(_i: int) -> ServerConfig:
        return ServerConfig(
            num_workers=2, eval_batch_size=4, plan_commit_batching=True,
            sched_config=SchedulerConfiguration(
                scheduler_algorithm=enums.SCHED_ALG_TPU_SOLVE),
            heartbeat_ttl=3600.0, gc_interval=3600.0, nack_timeout=900.0,
            failed_eval_followup_delay=3600.0,
            failed_eval_unblock_interval=0.5)

    tmp = tempfile.mkdtemp(prefix="nomad-mesh-smoke-")
    checker = InvariantChecker()
    try:
        cluster = RaftCluster(3, config_fn=config_fn, data_dir=tmp)
        cluster.start()
        try:
            leader = cluster.wait_for_leader(timeout=15.0)
            if leader is None:
                print("MESH SMOKE: FAIL — no leader elected")
                return 2
            for _ in range(nodes_n):
                n = mock.node()
                n.resources.cpu = 16000
                n.resources.memory_mb = 32768
                n.compute_class()
                leader.register_node(n)

            from ..tensor.solver import get_service
            svc0 = dict(get_service().stats)

            jobs = []
            for i in range(jobs_n):
                j = mock.batch_job()
                tg = j.task_groups[0]
                tg.count = count
                tg.tasks[0].resources.cpu = (50, 80, 120, 60)[i % 4]
                tg.tasks[0].resources.memory_mb = (48, 96, 64, 128)[i % 4]
                jobs.append(j)
                leader.register_job(j)

            deadline = time.time() + 240
            while True:
                if leader.server.wait_for_idle(
                        timeout=10.0, include_delayed=False) \
                        and leader.server.blocked.blocked_count() == 0:
                    break
                if time.time() > deadline:
                    print("MESH SMOKE: FAIL — pipeline did not drain")
                    return 2
                time.sleep(0.1)

            checker.check_convergence(cluster, timeout=30.0)
            checker.check_all(cluster)

            snap = leader.local_store.snapshot()
            placed = [a for a in snap.allocs()
                      if not a.terminal_status() and not a.server_terminal()]
            want = jobs_n * count
            if len(placed) != want:
                print(f"MESH SMOKE: FAIL — {len(placed)}/{want} "
                      f"placements landed")
                return 2
            if len({a.id for a in placed}) != len(placed):
                print("MESH SMOKE: FAIL — duplicate alloc ids")
                return 2

            svc = get_service().stats
            delta = {k: svc[k] - svc0.get(k, 0) for k in svc}
            if svc.get("mesh_devices", 0) != 8:
                print(f"MESH SMOKE: FAIL — solver mesh has "
                      f"{svc.get('mesh_devices', 0)} devices, wanted 8")
                return 2
            if delta.get("sharded", 0) < 1:
                print("MESH SMOKE: FAIL — no launch ran through the "
                      "node-sharded engine (sharded == 0)")
                return 2
            if delta.get("joint_launches", 0) < 1:
                print("MESH SMOKE: FAIL — no batch reached the joint "
                      "auction tier (joint_launches == 0)")
                return 2
            if delta.get("allgathers", 0) < 1:
                print("MESH SMOKE: FAIL — sharded launches ran but the "
                      "all-gather accounting stayed at 0")
                return 2
            if delta.get("retraces", 0) != 0:
                print(f"MESH SMOKE: FAIL — {delta['retraces']} warm "
                      f"retrace(s) under the no_retrace window")
                return 2
        finally:
            cluster.stop()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    dt = time.monotonic() - t0
    print(f"MESH SMOKE: ok — {want} placements via "
          f"{delta.get('sharded', 0)} sharded launch(es) "
          f"({delta.get('joint_launches', 0)} joint) on an 8-device "
          f"mesh, {delta.get('allgathers', 0)} all-gathers, "
          f"0 retraces, {checker.stats['checks']} invariant sweeps, "
          f"{dt:.1f}s")
    return 0


def snap_smoke(jobs_n: int = 200, nodes_n: int = 60, workers: int = 4,
               snapshot_threshold: int = 120) -> int:
    """Snapshot/compaction smoke (scripts/check.sh --snap-smoke): the
    e2e pipeline runs on a durable 3-node cluster with a snapshot
    threshold low enough that every replica snapshots + compacts under
    load. One follower is crashed and its data_dir wiped AFTER the
    leader has compacted past the wiped state, so the restart can only
    catch up via the chunked install-snapshot path — mid-traffic.
    Asserts: the wiped follower converges, zero acked-commit loss on
    every replica, alloc-set uniqueness on every replica, and the full
    invariant sweep passes."""
    import os
    import shutil

    from ..core.server import ServerConfig
    from ..raft.cluster import RaftCluster
    from .invariants import InvariantChecker

    t0 = time.monotonic()

    def config_fn(_i: int) -> ServerConfig:
        return ServerConfig(
            num_workers=workers, plan_commit_batching=True,
            eval_batch_size=8,
            heartbeat_ttl=3600.0, gc_interval=3600.0, nack_timeout=900.0,
            failed_eval_followup_delay=3600.0,
            failed_eval_unblock_interval=0.5)

    tmp = tempfile.mkdtemp(prefix="nomad-snap-smoke-")
    checker = InvariantChecker()
    try:
        cluster = RaftCluster(3, config_fn=config_fn, data_dir=tmp,
                              snapshot_threshold=snapshot_threshold)
        cluster.start()
        try:
            leader = cluster.wait_for_leader(timeout=15.0)
            if leader is None:
                print("SNAP SMOKE: FAIL — no leader elected")
                return 2
            # shrink the transfer chunk so the install is genuinely
            # multi-frame at this store size
            for s in cluster.servers.values():
                s.raft.snapshot_chunk_bytes = 64 * 1024

            for _ in range(nodes_n):
                leader.register_node(mock.node())
            jobs = []
            for _ in range(jobs_n):
                j = mock.job()
                j.task_groups[0].count = 1
                j.task_groups[0].tasks[0].resources.cpu = 100
                j.task_groups[0].tasks[0].resources.memory_mb = 64
                jobs.append(j)
                leader.store.upsert_job(j)
            evals = [mock.eval_for(j, create_time=time.time())
                     for j in jobs]
            leader.store.upsert_evals(evals)
            for ev in evals:
                leader.server.broker.enqueue(ev)

            # wipe window: some allocs committed (acked), many evals
            # still in flight, and the leader has already compacted —
            # so the wiped follower's entries are physically gone
            deadline = time.time() + 90
            while time.time() < deadline:
                snap = leader.local_store.snapshot()
                committed = [a.id for a in snap.allocs()]
                if len(committed) >= jobs_n // 4 \
                        and leader.raft.log.base_index > 0:
                    break
                time.sleep(0.002)
            else:
                print("SNAP SMOKE: FAIL — pipeline never reached the "
                      "wipe window (committed allocs + a compaction)")
                return 2
            acked = set(committed)
            leader_base = leader.raft.log.base_index

            victim_id = next(i for i, s in cluster.servers.items()
                             if s is not leader)
            old = cluster.crash(victim_id)
            shutil.rmtree(os.path.join(old.data_dir, "raft"),
                          ignore_errors=True)
            victim = cluster.restart(victim_id)

            # drain with the wiped follower racing its chunked install
            # against live plan traffic
            deadline = time.time() + 180
            while True:
                if leader.server._running \
                        and leader.server.wait_for_idle(
                            timeout=10.0, include_delayed=False) \
                        and leader.server.blocked.blocked_count() == 0:
                    snap = leader.local_store.snapshot()
                    placed = [a for a in snap.allocs()
                              if not a.terminal_status()
                              and not a.server_terminal()]
                    if len(placed) >= jobs_n:
                        break
                if time.time() > deadline:
                    print("SNAP SMOKE: FAIL — pipeline did not drain "
                          "after the follower wipe")
                    return 2
                time.sleep(0.1)

            checker.check_convergence(cluster, timeout=60.0)
            checker.check_all(cluster)

            # the wiped follower can't have replayed entries <= the
            # leader's pre-wipe base from its (empty) log: a base past
            # that point proves the chunked install delivered it
            if victim.raft.log.base_index < leader_base:
                print(f"SNAP SMOKE: FAIL — wiped follower base "
                      f"{victim.raft.log.base_index} < leader's "
                      f"pre-wipe base {leader_base}; catch-up did not "
                      f"go through install-snapshot")
                return 2
            if victim.raft.snapshots.last_index <= 0:
                print("SNAP SMOKE: FAIL — wiped follower has no "
                      "persisted snapshot after catch-up")
                return 2

            for sid, s in cluster.servers.items():
                snap = s.local_store.snapshot()
                ids = [a.id for a in snap.allocs()]
                if len(ids) != len(set(ids)):
                    print(f"SNAP SMOKE: FAIL — duplicate alloc ids on "
                          f"{sid}")
                    return 2
                lost = acked - set(ids)
                if lost:
                    print(f"SNAP SMOKE: FAIL — {len(lost)} acked "
                          f"alloc(s) missing on {sid}: "
                          f"{sorted(i[:8] for i in lost)[:5]}")
                    return 2
        finally:
            cluster.stop()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    dt = time.monotonic() - t0
    print(f"SNAP SMOKE: ok — {jobs_n} evals, {len(acked)} allocs acked "
          f"pre-wipe all present on every replica, wiped follower "
          f"caught up via chunked install (base {leader_base} -> "
          f"{victim.raft.log.base_index}), "
          f"{checker.stats['checks']} invariant sweeps, {dt:.1f}s")
    return 0


def swarm_smoke(nodes_n: int = 200, ttl: float = 2.0,
                crashes: int = 3) -> int:
    """Client-plane flap-churn smoke (scripts/check.sh --swarm-smoke):
    200 sim nodes heartbeating through the batch endpoints while a
    churn loop registers/deregisters a rolling slice and THREE leaders
    crash in sequence. Asserts: no stable node is ever wrongly marked
    down (check_node_liveness on every replica), silenced nodes expire
    only after a real >= TTL silence and recover on their next beat,
    allocs pushed to sim nodes are acked without loss, and the
    alloc-uniqueness + safety invariants hold."""
    import shutil

    from ..core.server import ServerConfig
    from ..raft.cluster import RaftCluster
    from ..structs import enums as _enums
    from .invariants import InvariantChecker
    from .swarm import Swarm

    t0 = time.monotonic()

    def config_fn(_i: int) -> ServerConfig:
        return ServerConfig(
            num_workers=2, plan_commit_batching=True, eval_batch_size=8,
            heartbeat_ttl=ttl, heartbeat_shards=4,
            heartbeat_expiry_rate=128.0,
            gc_interval=3600.0, nack_timeout=900.0,
            failed_eval_followup_delay=3600.0,
            failed_eval_unblock_interval=0.5)

    tmp = tempfile.mkdtemp(prefix="nomad-swarm-smoke-")
    checker = InvariantChecker()
    try:
        cluster = RaftCluster(3, config_fn=config_fn, data_dir=tmp)
        cluster.start()
        stop_churn = threading.Event()
        churn_thread = None
        swarm = None
        try:
            leader = cluster.wait_for_leader(timeout=15.0)
            if leader is None:
                print("SWARM SMOKE: FAIL — no leader elected")
                return 2

            def entry():
                return cluster.leader()

            swarm = Swarm(entry, nodes_n, ttl=ttl, interval=ttl / 4.0,
                          drivers=2, rpc_batch=64, ack=True)
            if swarm.register_all(chunk=50) != nodes_n:
                print("SWARM SMOKE: FAIL — fleet registration timed out")
                return 2

            # stable population: never churned, never silenced — these
            # must NEVER be marked down across all three failovers
            churn_pool = swarm.nodes[-60:]
            silence_pool = swarm.nodes[:20]
            stable = swarm.nodes[20:-60]

            # a real workload rides the sim nodes: its allocs must be
            # pushed out via delta sync and acked back without loss
            for _ in range(30):
                j = mock.job()
                j.task_groups[0].count = 2
                j.task_groups[0].tasks[0].resources.cpu = 50
                j.task_groups[0].tasks[0].resources.memory_mb = 32
                leader.register_job(j)

            swarm.start()

            def churn():
                i = 0
                while not stop_churn.is_set():
                    batch = churn_pool[i % 3::3]
                    swarm.deregister(batch)
                    if stop_churn.wait(0.3):
                        return
                    swarm.register_all(chunk=50, deadline_s=20.0,
                                       subset=batch)
                    if stop_churn.wait(0.3):
                        return
                    i += 1

            churn_thread = threading.Thread(target=churn, daemon=True,
                                            name="swarm-churn")
            churn_thread.start()

            for round_i in range(crashes):
                victim = cluster.wait_for_leader(timeout=15.0)
                if victim is None:
                    print("SWARM SMOKE: FAIL — lost the leader before "
                          f"crash round {round_i}")
                    return 2
                cluster.crash(victim.id)
                fresh = cluster.wait_for_leader(timeout=20.0)
                if fresh is None:
                    print("SWARM SMOKE: FAIL — no leader after crash "
                          f"round {round_i}")
                    return 2
                cluster.restart(victim.id)
                # let the fleet beat through the new leader's grace
                # window before sweeping
                time.sleep(ttl * 1.5)
                checker.check_all(cluster)
                checker.check_node_liveness(cluster, swarm=swarm, ttl=ttl)

            stop_churn.set()
            churn_thread.join(timeout=30.0)

            # no stable node may ever have been wrongly expired
            leader = cluster.wait_for_leader(timeout=15.0)
            deadline = time.time() + 60
            stable_ids = {sn.id for sn in stable}
            while True:
                snap = leader.local_store.snapshot()
                bad = [n.id for n in snap.nodes()
                       if n.id in stable_ids
                       and n.status != _enums.NODE_STATUS_READY]
                if not bad:
                    break
                if time.time() > deadline:
                    print(f"SWARM SMOKE: FAIL — {len(bad)} stable "
                          f"node(s) not ready after churn+crashes: "
                          f"{bad[:5]}")
                    return 2
                time.sleep(0.2)

            # silenced nodes must expire (real silence >= TTL)...
            swarm.silence(silence_pool)
            silence_ids = {sn.id for sn in silence_pool}
            deadline = time.time() + ttl * 10 + 30
            while True:
                snap = leader.local_store.snapshot()
                down = [n.id for n in snap.nodes()
                        if n.id in silence_ids
                        and n.status in (_enums.NODE_STATUS_DOWN,
                                         _enums.NODE_STATUS_DISCONNECTED)]
                if len(down) == len(silence_ids):
                    break
                if time.time() > deadline:
                    print(f"SWARM SMOKE: FAIL — only {len(down)}/"
                          f"{len(silence_ids)} silenced nodes expired")
                    return 2
                time.sleep(0.2)
            checker.check_node_liveness(cluster, swarm=swarm, ttl=ttl)

            # ...and recover to ready on their next successful beat
            swarm.unsilence(silence_pool)
            deadline = time.time() + 60
            while True:
                snap = leader.local_store.snapshot()
                ready = [n.id for n in snap.nodes()
                         if n.id in silence_ids
                         and n.status == _enums.NODE_STATUS_READY]
                if len(ready) == len(silence_ids):
                    break
                if time.time() > deadline:
                    print(f"SWARM SMOKE: FAIL — only {len(ready)}/"
                          f"{len(silence_ids)} silenced nodes recovered")
                    return 2
                time.sleep(0.2)

            # every live desired-run alloc on a registered sim node must
            # end up acked running — delta push + batched acks, no loss
            deadline = time.time() + 120
            while True:
                leader = cluster.wait_for_leader(timeout=15.0)
                snap = leader.local_store.snapshot()
                pending = [a.id for a in snap.allocs()
                           if a.node_id in swarm.ids()
                           and not a.terminal_status()
                           and not a.server_terminal()
                           and a.desired_status == _enums.ALLOC_DESIRED_RUN
                           and a.client_status != _enums.ALLOC_CLIENT_RUNNING]
                placed = [a for a in snap.allocs()
                          if not a.terminal_status()
                          and not a.server_terminal()]
                if not pending and placed:
                    break
                if time.time() > deadline:
                    print(f"SWARM SMOKE: FAIL — {len(pending)} alloc "
                          f"ack(s) still missing: {pending[:5]}")
                    return 2
                time.sleep(0.2)

            checker.check_convergence(cluster, timeout=30.0)
            checker.check_all(cluster)
            checker.check_node_liveness(cluster, swarm=swarm, ttl=ttl)
            beats = swarm.total_beats()
            acked = len(swarm.acked_ids)
            expiries = sum(
                s.server.heartbeats.stats["invalidated"]
                for s in cluster.servers.values() if not s.crashed)
        finally:
            stop_churn.set()
            if swarm is not None:
                swarm.stop()
            if churn_thread is not None:
                churn_thread.join(timeout=5.0)
            cluster.stop()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    dt = time.monotonic() - t0
    print(f"SWARM SMOKE: ok — {nodes_n} sim nodes, {beats} heartbeats, "
          f"{crashes} leader crashes, {len(silence_pool)} real expiries "
          f"(total {expiries}) all attributed, {acked} allocs acked, "
          f"{checker.stats['checks']} invariant sweeps, {dt:.1f}s")
    return 0


def swarm_scale_smoke(nodes_n: int = 50000, ttl: float = 10.0,
                      jobs_n: int = 150) -> int:
    """The ROADMAP acceptance run: 50K+ sim nodes heartbeating at the
    production TTL against a live 3-node cluster WHILE the e2e3 write
    pipeline runs, one leader crash/failover mid-stream, and ZERO
    missed-TTL false positives — verified by check_node_liveness on
    every replica. Heavy (minutes); run explicitly via
    `python -m nomad_tpu.chaos --swarm-scale [N]`."""
    import shutil

    from ..core.server import ServerConfig
    from ..raft.cluster import RaftCluster
    from ..structs import enums as _enums
    from .invariants import InvariantChecker
    from .swarm import Swarm

    t0 = time.monotonic()

    def config_fn(_i: int) -> ServerConfig:
        return ServerConfig(
            num_workers=4, plan_commit_batching=True, eval_batch_size=8,
            heartbeat_ttl=ttl, heartbeat_shards=8,
            gc_interval=3600.0, nack_timeout=900.0,
            failed_eval_followup_delay=3600.0,
            failed_eval_unblock_interval=0.5)

    tmp = tempfile.mkdtemp(prefix="nomad-swarm-scale-")
    checker = InvariantChecker()
    try:
        cluster = RaftCluster(3, config_fn=config_fn, data_dir=tmp,
                              snapshot_threshold=8192)
        cluster.start()
        swarm = None
        try:
            leader = cluster.wait_for_leader(timeout=15.0)
            if leader is None:
                print("SWARM SCALE: FAIL — no leader elected")
                return 2

            def entry():
                return cluster.leader()

            swarm = Swarm(entry, nodes_n, ttl=ttl, interval=3.0,
                          drivers=8, rpc_batch=1024, ack=True)
            # drivers first, registration second: a real fleet ramps —
            # each node starts heartbeating the moment it registers. A
            # fleet-sized registration takes several TTLs, so arming
            # 50K timers and only then starting the beats would expire
            # (and revive) every early chunk purely as a harness
            # artifact.
            swarm.start()
            reg_t0 = time.monotonic()
            if swarm.register_all(chunk=1000, deadline_s=600.0) != nodes_n:
                print("SWARM SCALE: FAIL — fleet registration timed out")
                return 2
            reg_dt = time.monotonic() - reg_t0

            # registration load can move leadership; re-resolve, and
            # retry workload proposals through any further election
            def propose(fn):
                nonlocal leader
                deadline = time.time() + 60
                while True:
                    try:
                        return fn(leader)
                    except Exception:
                        if time.time() > deadline:
                            raise
                        time.sleep(0.25)
                        leader = (cluster.wait_for_leader(timeout=30.0)
                                  or leader)

            leader = cluster.wait_for_leader(timeout=30.0) or leader

            # e2e3 write pipeline in parallel with the heartbeat storm
            jobs = []
            for _ in range(jobs_n):
                j = mock.job()
                j.task_groups[0].count = 1
                j.task_groups[0].tasks[0].resources.cpu = 100
                j.task_groups[0].tasks[0].resources.memory_mb = 64
                jobs.append(j)
                propose(lambda srv: srv.store.upsert_job(j))
            evals = [mock.eval_for(j, create_time=time.time())
                     for j in jobs]
            propose(lambda srv: srv.store.upsert_evals(evals))
            for ev in evals:
                propose(lambda srv: srv.server.broker.enqueue(ev))

            deadline = time.time() + 120
            while time.time() < deadline:
                snap = leader.local_store.snapshot()
                if len([a for a in snap.allocs()]) >= jobs_n // 4:
                    break
                time.sleep(0.05)
            else:
                print("SWARM SCALE: FAIL — pipeline never reached the "
                      "crash window")
                return 2

            hb_before = swarm.total_beats()
            victim = cluster.wait_for_leader(timeout=15.0) or leader
            cluster.crash(victim.id)
            fresh = cluster.wait_for_leader(timeout=30.0)
            if fresh is None:
                print("SWARM SCALE: FAIL — no leader after the crash")
                return 2
            cluster.restart(victim.id)

            # beat through the new leader's grace window + one full TTL
            time.sleep(ttl * 2.0)

            checker.check_all(cluster)

            # ZERO missed-TTL false positives: no sim node may END UP
            # down on any live replica. If election churn stalled a
            # driver past the TTL, that expiry is a TRUE positive — but
            # it must be attributed (checker, below) and must heal via
            # the heartbeat revival path, so recovery gets a bounded
            # window before the hard zero-down assertion.
            sim_ids = set(swarm.ids())
            down_states = (_enums.NODE_STATUS_DOWN,
                           _enums.NODE_STATUS_DISCONNECTED)

            def down_on(s):
                snap = s.local_store.snapshot()
                return [n.id for n in snap.nodes()
                        if n.id in sim_ids and n.status in down_states]

            recover_deadline = time.time() + 60.0
            while time.time() < recover_deadline:
                if not any(down_on(s) for s in cluster.servers.values()
                           if not s.crashed):
                    break
                time.sleep(0.5)
            checker.check_node_liveness(cluster, swarm=swarm, ttl=ttl)
            for s in cluster.servers.values():
                if s.crashed:
                    continue
                wrong = down_on(s)
                if wrong:
                    print(f"SWARM SCALE: FAIL — {len(wrong)} node(s) "
                          f"still down on {s.id} after the recovery "
                          f"window: {wrong[:5]}")
                    return 2

            hb_after = swarm.total_beats()
            # every expiry that did fire was verified attributable to a
            # real >= TTL silence by check_node_liveness; surface count
            expiries = sum(
                s.server.heartbeats.stats["invalidated"]
                for s in cluster.servers.values() if not s.crashed)
            checker.check_convergence(cluster, timeout=60.0)
            snap = cluster.wait_for_leader(timeout=15.0).local_store.snapshot()
            placed = len([a for a in snap.allocs()
                          if not a.terminal_status()
                          and not a.server_terminal()])
        finally:
            if swarm is not None:
                swarm.stop()
            cluster.stop()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    dt = time.monotonic() - t0
    rate = (hb_after - hb_before) / max(dt, 1e-9)
    print(f"SWARM SCALE: ok — {nodes_n} sim nodes at TTL {ttl:.0f}s, "
          f"{swarm.total_beats()} heartbeats "
          f"({hb_after - hb_before} post-crash, ~{rate:.0f}/s overall), "
          f"{placed} live allocs placed by the concurrent pipeline, "
          f"registration {reg_dt:.1f}s, {expiries} attributed "
          f"expiries and ZERO missed-TTL false positives across the "
          f"failover, {checker.stats['checks']} invariant sweeps, "
          f"{dt:.1f}s")
    return 0


def watch_smoke(watchers_per_server: int = 12) -> int:
    """Leader-failover-mid-watch smoke (scripts/check.sh --watch-smoke):
    blocking queries + event subscriptions parked on ALL 3 servers of a
    live cluster while the leader crashes. Asserts: every parked query
    on a survivor completes with the post-failover result at a higher
    index; subscriptions on survivors deliver the post-failover event;
    fresh reads against the dead server fail fast with
    X-Nomad-KnownLeader=false; and the stale-read bound
    (X-Nomad-LastContact) holds on survivors across the transition."""
    import json
    import urllib.error
    import urllib.request

    from ..api.http import HTTPAgent
    from ..core.server import ServerConfig

    t0 = time.monotonic()
    cluster = RaftCluster(3, config_fn=lambda i: ServerConfig(
        num_workers=0, heartbeat_ttl=3600.0, gc_interval=3600.0))
    agents = {}
    failures: list = []
    try:
        cluster.start()
        leader = cluster.wait_for_leader(15.0)
        if leader is None:
            print("WATCH SMOKE: FAIL — no leader elected")
            return 2
        for sid, srv in cluster.servers.items():
            agents[sid] = HTTPAgent(srv.server, port=0, writer=srv).start()

        leader.register_node(mock.node())

        def get(sid, path, timeout=10.0):
            r = urllib.request.urlopen(f"{agents[sid].address}{path}",
                                       timeout=timeout)
            return json.loads(r.read()), r.headers

        # pre-crash: every server answers with staleness headers
        want = 0
        for sid in cluster.servers:
            nodes, hdrs = get(sid, "/v1/nodes")
            if len(nodes) != 1:
                failures.append(f"{sid}: pre-crash read saw {len(nodes)}")
            if hdrs["X-Nomad-KnownLeader"] != "true":
                failures.append(f"{sid}: pre-crash KnownLeader false")
            lc = int(hdrs["X-Nomad-LastContact"])
            if lc >= 2000:
                failures.append(f"{sid}: pre-crash LastContact {lc}ms")
            want = max(want, int(hdrs["X-Nomad-Index"]))

        # park blocking queries on all 3 servers + one event
        # subscription per server
        results: dict = {}
        lock = threading.Lock()

        def block(tag, sid, wait_s):
            try:
                data, hdrs = get(
                    sid, f"/v1/nodes?index={want}&wait={wait_s}",
                    timeout=wait_s + 20.0)
                out = ("ok", len(data), int(hdrs["X-Nomad-Index"]))
            except (urllib.error.URLError, OSError) as e:
                out = ("err", repr(e), None)
            with lock:
                results[tag] = out

        subs = {sid: srv.server.events.subscribe({"Node": ["*"]})
                for sid, srv in cluster.servers.items()}
        sub_got: dict = {}

        def watch_events(sid, timeout):
            evs = subs[sid].next_events(timeout=timeout)
            with lock:
                sub_got[sid] = [e.type for e in evs]

        victim = leader.id
        threads = []
        for sid in cluster.servers:
            # parked watchers on the (about to be) dead server can only
            # time out — keep their windows short so the smoke stays fast
            wait_s = 6.0 if sid == victim else 20.0
            for i in range(watchers_per_server):
                threads.append(threading.Thread(
                    target=block, args=(f"{sid}/{i}", sid, wait_s)))
            threads.append(threading.Thread(
                target=watch_events,
                args=(sid, 8.0 if sid == victim else 25.0)))
        for t in threads:
            t.start()
        deadline = time.time() + 10.0
        while time.time() < deadline:
            parked = sum(s.store.watches.parked()
                         for s in cluster.servers.values())
            if parked >= 3 * watchers_per_server:
                break
            time.sleep(0.05)
        else:
            failures.append(f"only {parked} queries parked")

        # crash the leader mid-watch, write through a survivor
        cluster.crash(victim)
        new_leader = cluster.wait_for_leader(15.0)
        if new_leader is None:
            print("WATCH SMOKE: FAIL — no post-crash leader")
            return 2
        _live_entry(cluster).register_node(mock.node())

        for t in threads:
            t.join(timeout=40.0)
        if any(t.is_alive() for t in threads):
            failures.append("watcher threads wedged")

        for tag, out in sorted(results.items()):
            sid = tag.split("/")[0]
            if sid == victim:
                continue  # below
            if out[0] != "ok" or out[1] != 2 or out[2] <= want:
                failures.append(f"survivor watcher {tag}: {out}")
        # dead-server watchers: a timed-out long-poll returning the old
        # state at the old index is a CONSISTENT bounded-stale answer;
        # a torn connection is a fail-fast. Both are allowed — seeing
        # the post-crash write from the dead server's store is not.
        for tag, out in sorted(results.items()):
            if not tag.startswith(victim):
                continue
            if out[0] == "ok" and out[1] != 1:
                failures.append(f"dead-server watcher {tag}: {out}")
        for sid in cluster.servers:
            if sid == victim:
                continue
            if sub_got.get(sid) != ["node-upsert"]:
                failures.append(
                    f"{sid}: subscription saw {sub_got.get(sid)}")

        # fresh reads post-failover: survivors answer with a fresh
        # stale bound; the dead server fails fast, KnownLeader=false
        for sid in cluster.servers:
            if sid == victim:
                continue
            nodes, hdrs = get(sid, "/v1/nodes")
            if len(nodes) != 2:
                failures.append(f"{sid}: post-crash read {len(nodes)}")
            if hdrs["X-Nomad-KnownLeader"] != "true":
                failures.append(f"{sid}: post-crash KnownLeader false")
            if int(hdrs["X-Nomad-LastContact"]) >= 2000:
                failures.append(
                    f"{sid}: post-crash LastContact "
                    f"{hdrs['X-Nomad-LastContact']}ms")
        t1 = time.monotonic()
        try:
            get(victim, "/v1/nodes", timeout=10.0)
            failures.append("dead server served a read-index GET")
        except urllib.error.HTTPError as e:
            if e.code != 503:
                failures.append(f"dead server replied {e.code}")
            if e.headers.get("X-Nomad-KnownLeader") != "false":
                failures.append("dead server claimed KnownLeader")
        except (urllib.error.URLError, OSError):
            pass  # connection-level death is fail-fast too
        if time.monotonic() - t1 > 5.0:
            failures.append("dead-server read was not fail-fast")

        if failures:
            print("WATCH SMOKE: FAIL —")
            for f in failures[:20]:
                print(f"  {f}")
            return 2
    finally:
        for sub in locals().get("subs", {}).values():
            sub.close()
        for a in agents.values():
            a.stop()
        cluster.stop()
    dt = time.monotonic() - t0
    print(f"WATCH SMOKE: ok — {3 * watchers_per_server} parked queries "
          f"+ 3 subscriptions across a leader crash: survivors woke "
          f"consistent, dead server failed fast, stale bounds held, "
          f"{dt:.1f}s")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="python -m nomad_tpu.chaos")
    parser.add_argument("--seed", type=int, default=None,
                        help="fault seed (default: NOMAD_TPU_CHAOS_SEED or 0)")
    parser.add_argument("--raft-smoke", action="store_true",
                        help="run the raft group-commit crash smoke "
                             "instead of the scenario smoke")
    parser.add_argument("--e2e-smoke", action="store_true",
                        help="run the full-pipeline smoke (300 evals, "
                             "3 nodes, leader restart mid-stream) "
                             "instead of the scenario smoke")
    parser.add_argument("--solve-smoke", action="store_true",
                        help="run the global-batch solve smoke "
                             "(batched workers under tpu-solve; joint "
                             "launch, score dominance, alloc "
                             "uniqueness) instead of the scenario smoke")
    parser.add_argument("--mesh-smoke", action="store_true",
                        help="run the multi-chip C2M smoke (live "
                             "3-node cluster with the solver on an "
                             "8-virtual-device mesh; sharded joint "
                             "launches, zero retraces, alloc "
                             "uniqueness on every replica) instead of "
                             "the scenario smoke — export XLA_FLAGS="
                             "--xla_force_host_platform_device_count=8 "
                             "first (scripts/check.sh --mesh-smoke "
                             "does)")
    parser.add_argument("--snap-smoke", action="store_true",
                        help="run the snapshot/compaction smoke (low "
                             "snapshot threshold under e2e load, one "
                             "follower wiped + restarted, catch-up via "
                             "chunked install-snapshot) instead of the "
                             "scenario smoke")
    parser.add_argument("--swarm-smoke", action="store_true",
                        help="run the client-plane swarm smoke (200 sim "
                             "nodes flap-churning while 3 leaders crash "
                             "in sequence; liveness + alloc-uniqueness "
                             "on every replica) instead of the scenario "
                             "smoke")
    parser.add_argument("--load-smoke", action="store_true",
                        help="run the overload smoke (3-node cluster, "
                             "10x open-loop submit burst, leader crash "
                             "mid-burst; tier-0 heartbeat SLO, zero "
                             "acked-work loss, overload tier ordering) "
                             "instead of the scenario smoke")
    parser.add_argument("--flow-smoke", action="store_true",
                        help="run the event-completeness smoke (e2e "
                             "pipeline with nomadflow shadow replicas "
                             "force-armed on every server across a "
                             "leader crash; zero shadow divergences) "
                             "instead of the scenario smoke")
    parser.add_argument("--state-smoke", action="store_true",
                        help="run the incremental-state smoke (e2e "
                             "pipeline riding the device-resident O(Δ) "
                             "usage base across a leader crash AND a "
                             "forced event-ring truncation; parity "
                             "clean on every feed) instead of the "
                             "scenario smoke")
    parser.add_argument("--watch-smoke", action="store_true",
                        help="run the read-path failover smoke (blocking "
                             "queries + event subscriptions parked on "
                             "all 3 servers across a leader crash; "
                             "stale-read bounds + fail-fast on the dead "
                             "server) instead of the scenario smoke")
    parser.add_argument("--swarm-scale", type=int, nargs="?",
                        const=50000, default=None, metavar="N",
                        help="run the fleet-scale acceptance smoke: N "
                             "(default 50000) sim nodes at production "
                             "TTL against a live 3-node cluster with "
                             "the e2e pipeline + a leader crash; zero "
                             "missed-TTL false positives (minutes)")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO if args.verbose else logging.WARNING,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    import os
    if args.seed is not None:
        os.environ["NOMAD_TPU_CHAOS_SEED"] = str(args.seed)
    if args.raft_smoke:
        return raft_smoke()
    if args.e2e_smoke:
        return e2e_smoke()
    if args.solve_smoke:
        return solve_smoke()
    if args.mesh_smoke:
        return mesh_smoke()
    if args.snap_smoke:
        return snap_smoke()
    if args.swarm_smoke:
        return swarm_smoke()
    if args.load_smoke:
        return load_smoke()
    if args.flow_smoke:
        return flow_smoke()
    if args.state_smoke:
        return state_smoke()
    if args.watch_smoke:
        return watch_smoke()
    if args.swarm_scale is not None:
        return swarm_scale_smoke(nodes_n=args.swarm_scale)

    t0 = time.monotonic()
    with tempfile.TemporaryDirectory(prefix="nomad-chaos-") as tmp:
        cluster = RaftCluster(3, data_dir=tmp)
        cluster.start()
        try:
            runner = build_scenario(cluster)
            try:
                report = runner.run()
            except InvariantViolation as e:
                print(f"CHAOS SMOKE: FAIL — {e} "
                      f"(reproduce: NOMAD_TPU_CHAOS_SEED={runner.seed})")
                return 2
        finally:
            cluster.stop()
    dt = time.monotonic() - t0
    print(f"CHAOS SMOKE: ok — {len(report['steps'])} steps, "
          f"seed={report['seed']}, faults={report['faults']}, "
          f"{dt:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
