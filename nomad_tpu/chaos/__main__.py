"""Chaos smoke: one scripted partition + crash scenario on a durable
3-node cluster, fixed seed, well under a minute.

    python -m nomad_tpu.chaos [--seed N]

Exit 0 when every invariant holds; 2 on a violation (the CI gate in
scripts/check.sh). This is the smallest end-to-end proof that the
fault layer, the recovery paths, and the invariant sweep all work —
the full scenario matrix lives in tests/test_chaos.py.
"""

from __future__ import annotations

import argparse
import logging
import sys
import tempfile
import time

from .. import mock
from ..raft.cluster import RaftCluster
from .invariants import InvariantViolation
from .runner import ScenarioRunner, seed_from_env

log = logging.getLogger("nomad_tpu.chaos")


def _live_entry(cluster):
    return next(s for s in cluster.servers.values() if not s.crashed)


def build_scenario(cluster) -> ScenarioRunner:
    r = ScenarioRunner(cluster, seed=seed_from_env())

    @r.step("elect + seed workload")
    def _seed(r):
        leader = r.wait_for_leader()
        entry = _live_entry(cluster)
        for _ in range(2):
            entry.register_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 2
        entry.register_job(job)
        leader.server.wait_for_idle(15.0)

    @r.step("cut the leader's outbound links (directed partition)")
    def _cut(r):
        leader = r.wait_for_leader()
        others = [sid for sid in cluster.servers if sid != leader.id]
        for sid in others:
            cluster.transport.partition_link(leader.id, sid)
        # followers miss heartbeats and elect among themselves; the old
        # leader still hears the higher term and steps down
        deadline = time.time() + 10
        while time.time() < deadline:
            fresh = cluster.leader()
            if fresh is not None and fresh.id != leader.id:
                return
            time.sleep(0.05)
        raise InvariantViolation("no replacement leader after directed cut")

    @r.step("write through the new leader, then heal")
    def _write_and_heal(r):
        entry = _live_entry(cluster)
        entry.register_node(mock.node())
        r.heal_and_converge()

    @r.step("crash the leader mid-write, restart, converge")
    def _crash_restart(r):
        leader = r.wait_for_leader()
        entry = next(s for s in cluster.servers.values()
                     if not s.crashed and s.id != leader.id)
        cluster.crash(leader.id)
        entry.register_node(mock.node())  # forwarded to the new leader
        cluster.restart(leader.id)
        r.heal_and_converge(timeout=20.0)

    return r


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="python -m nomad_tpu.chaos")
    parser.add_argument("--seed", type=int, default=None,
                        help="fault seed (default: NOMAD_TPU_CHAOS_SEED or 0)")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO if args.verbose else logging.WARNING,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    import os
    if args.seed is not None:
        os.environ["NOMAD_TPU_CHAOS_SEED"] = str(args.seed)

    t0 = time.monotonic()
    with tempfile.TemporaryDirectory(prefix="nomad-chaos-") as tmp:
        cluster = RaftCluster(3, data_dir=tmp)
        cluster.start()
        try:
            runner = build_scenario(cluster)
            try:
                report = runner.run()
            except InvariantViolation as e:
                print(f"CHAOS SMOKE: FAIL — {e} "
                      f"(reproduce: NOMAD_TPU_CHAOS_SEED={runner.seed})")
                return 2
        finally:
            cluster.stop()
    dt = time.monotonic() - t0
    print(f"CHAOS SMOKE: ok — {len(report['steps'])} steps, "
          f"seed={report['seed']}, faults={report['faults']}, "
          f"{dt:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
