"""nomadchaos: deterministic fault injection for the replicated control
plane.

Pieces (see ROBUSTNESS.md for the fault model and workflow):

- ``FaultPlan`` / ``LinkFaults`` — seeded per-message verdicts (drop,
  delay, duplicate, reorder) plus scripted directed link cuts,
  consulted by InProcTransport and SocketTransport;
- ``FSFaults`` — disk-fault shim (ENOSPC/EIO at the durable-storage
  chokepoints) plus torn-tail helpers;
- ``InvariantChecker`` — election safety, log matching, committed
  durability, FSM convergence, alloc reschedule;
- ``ScenarioRunner`` — scripted steps with the safety sweep between
  them, seeded from ``NOMAD_TPU_CHAOS_SEED``.
"""

from .fsfaults import FSFaults, tear_log_tail, truncate_log_mid_line
from .invariants import InvariantChecker, InvariantViolation
from .plan import FaultPlan, LinkFaults, Verdict
from .runner import ScenarioRunner, seed_from_env

__all__ = [
    "FaultPlan", "LinkFaults", "Verdict",
    "FSFaults", "tear_log_tail", "truncate_log_mid_line",
    "InvariantChecker", "InvariantViolation",
    "ScenarioRunner", "seed_from_env",
]
