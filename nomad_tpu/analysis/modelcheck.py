"""nomadcheck dynamic prong: a deterministic interleaving model checker.

Where nomadsan (sanitizer.py) observes the ONE interleaving the OS
happens to schedule, nomadcheck OWNS the schedule: while a scenario
runs, ``threading.Thread``/``Lock``/``RLock``/``Condition``/``Event``/
``Timer`` are replaced with cooperative versions driven by one
scheduler, so exactly one thread executes at a time and every yield
point (lock acquire/release, cond wait/notify, thread start/join,
sleep) asks a seeded policy which thread runs next. The same seed
replays the same schedule bit-for-bit (loom/Shuttle style), so any
interleaving bug a sweep finds is a one-line repro.

Model
-----
- **Yield points**: lock acquire (before), lock release (after),
  notify (after), thread start (after), plus every blocking operation
  (cond wait, event wait, join, sleep). Code between yield points runs
  atomically — the model checks lock/condvar protocol races, not
  data-word tearing (nomadsan's lockset prong covers unlocked access).
- **Virtual clock**: ``time.time``/``monotonic`` return a virtual
  clock for managed threads (+1µs per scheduling step). Timed waits
  and timers fire ONLY when no thread is runnable (earliest virtual
  deadline first): timeouts "may happen eventually", never preempt
  real progress, and are deterministic.
- **Deadlock**: every live thread blocked with no timed waiter or
  pending timer to fire → reported with each thread's block site.
- **Livelock**: the schedule exceeds ``max_steps`` without the
  scenario finishing → reported with the trace tail.
- **Thread leaks**: tasks still alive when the scenario's main
  function returns → reported by name (shutdown-protocol bugs).
- **Schedule encoding**: the trace is ``["<step>:<thread>:<op>", ...]``
  — the full decision sequence. Replay = same seed + same policy;
  identical traces ⇒ identical outcomes.

Policies: ``random`` picks uniformly among runnable threads at every
yield point; ``pbound`` is preemption-bounded exploration (stay on the
running thread, spend a small budget of forced preemptions at
rng-chosen points) — the cheap way to hit the "K context switches"
bugs that uniform sampling dilutes.

Scenarios (``SCENARIOS``) drive REAL control-plane objects — RaftNode
with its log-writer/replicators, PlanApplier's proposer/reaper
pipeline, EvalBroker batch dequeue — and assert the chaos
``InvariantChecker`` safety properties plus scenario-local liveness.
``raft_commit`` optionally composes with the chaos FSFaults disk shim
(an EIO torn mid-schedule into a batch append). ``NOMAD_TPU_CHECK_SEED``
replays a sweep seed, mirroring ``NOMAD_TPU_CHAOS_SEED``.

Caveats: managed code must not block inside C (``queue.SimpleQueue``,
``ThreadPoolExecutor`` worker loops) — invisible to the scheduler.
Scenarios avoid those paths. Replay is guaranteed within a process;
across processes it additionally requires a fixed PYTHONHASHSEED if
the covered code iterates sets of strings (current scenarios do not).
"""

from __future__ import annotations

import _thread
import random
import threading
import time
import types
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

_REAL_TIME = time.time
_REAL_MONOTONIC = time.monotonic
_REAL_SLEEP = time.sleep
_REAL_THREAD = threading.Thread

# how long a parked OS thread waits for its grant before declaring the
# scheduler itself wedged (real seconds; a backstop for checker bugs,
# never hit by a correct run)
_GATE_STALL_S = 60.0

_ACTIVE: Optional["Scheduler"] = None


def current_scheduler() -> Optional["Scheduler"]:
    return _ACTIVE


class _Abort(BaseException):
    """Unwinds managed threads after a finding; BaseException so the
    code under test's ``except Exception`` handlers can't swallow it."""


class CheckFailure(Exception):
    """A scenario failed under some schedule (assertion, invariant
    violation, deadlock, livelock, or thread leak)."""


@dataclass
class CheckResult:
    scenario: str
    seed: int
    policy: str
    steps: int
    trace: List[str]
    error: Optional[str] = None          # rendered failure, or None
    error_type: str = ""                 # exception class name
    leaked: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return self.error is None

    def render(self) -> str:
        status = "ok" if self.ok else f"FAIL [{self.error_type}]"
        head = (f"{self.scenario} seed={self.seed} policy={self.policy} "
                f"steps={self.steps}: {status}")
        if self.ok:
            return head
        tail = " | ".join(self.trace[-8:])
        return f"{head}\n  {self.error}\n  trace tail: {tail}"


class _Task:
    __slots__ = ("tid", "name", "gate", "state", "block_kind",
                 "block_obj", "wake_reason", "deadline", "thread",
                 "abort_granted")

    def __init__(self, tid: int, name: str, thread=None):
        self.tid = tid
        self.name = name
        self.gate = _thread.allocate_lock()
        self.gate.acquire()              # parked until granted
        self.state = "runnable"          # runnable|running|blocked|finished
        self.block_kind = ""
        self.block_obj = None
        self.wake_reason = ""
        self.deadline: Optional[float] = None
        self.thread = thread
        self.abort_granted = False


class DeadlockError(CheckFailure):
    pass


class LivelockError(CheckFailure):
    pass


class ThreadLeakError(CheckFailure):
    pass


# --------------------------------------------------------------------
# schedule policies
# --------------------------------------------------------------------

class RandomPolicy:
    name = "random"

    def __init__(self, seed: int):
        self.rng = random.Random(seed)

    def pick(self, sch: "Scheduler", choices: List[_Task]) -> _Task:
        return choices[self.rng.randrange(len(choices))]


class PreemptionBoundedPolicy:
    """Run the current thread until it blocks, spending a small budget
    of forced preemptions at rng-chosen yield points."""

    name = "pbound"

    def __init__(self, seed: int, budget: int = 3, rate: float = 0.1):
        self.rng = random.Random(seed)
        self.budget = budget
        self.rate = rate

    def pick(self, sch: "Scheduler", choices: List[_Task]) -> _Task:
        cur = sch.current
        if cur in choices:
            others = [c for c in choices if c is not cur]
            if (others and self.budget > 0
                    and self.rng.random() < self.rate):
                self.budget -= 1
                return others[self.rng.randrange(len(others))]
            return cur
        return choices[self.rng.randrange(len(choices))]


POLICIES: Dict[str, Callable[[int], object]] = {
    "random": RandomPolicy,
    "pbound": PreemptionBoundedPolicy,
}


# --------------------------------------------------------------------
# the scheduler
# --------------------------------------------------------------------

class Scheduler:
    def __init__(self, policy, max_steps: int = 50_000):
        self.policy = policy
        self.max_steps = max_steps
        self.tasks: Dict[int, _Task] = {}
        self.idents: Dict[int, _Task] = {}   # OS thread ident -> task
        self.current: Optional[_Task] = None
        self.step = 0
        self.trace: List[str] = []
        self.vclock = 1_700_000_000.0        # arbitrary fixed epoch
        self.timers: List["MCTimer"] = []
        self.aborting = False
        self.error: Optional[BaseException] = None
        self._next_tid = 0
        self._abort_mu = _thread.allocate_lock()

    # -- registration --------------------------------------------------

    def register_main(self) -> _Task:
        task = self._new_task("main")
        task.state = "running"
        self.current = task
        self.idents[threading.get_ident()] = task
        return task

    def _new_task(self, name: str, thread=None) -> _Task:
        tid = self._next_tid
        self._next_tid += 1
        # keep names unique but readable: append tid only on collision
        if any(t.name == name for t in self.tasks.values()):
            name = f"{name}#{tid}"
        task = _Task(tid, name, thread)
        self.tasks[tid] = task
        return task

    def me(self) -> Optional[_Task]:
        return self.idents.get(threading.get_ident())

    def alive_named(self, prefix: str) -> int:
        return sum(1 for t in self.tasks.values()
                   if t.state != "finished" and t.name.startswith(prefix))

    # -- scheduling core ----------------------------------------------

    def _sorted_runnable(self) -> List[_Task]:
        return [t for t in sorted(self.tasks.values(),
                                  key=lambda t: t.tid)
                if t.state == "runnable"]

    def _record(self, task: _Task, op: str) -> None:
        self.step += 1
        self.vclock += 1e-6
        self.trace.append(f"{self.step}:{task.name}:{op}")
        if self.step > self.max_steps:
            self._begin_abort(LivelockError(
                f"no completion after {self.max_steps} steps "
                f"(livelock or runaway loop)"))
            raise _Abort()

    def switch(self, op: str) -> None:
        """Yield point for a RUNNING task: optionally hand off."""
        me = self.me()
        if me is None or me is not self.current or me.state != "running":
            return
        if self.aborting:
            raise _Abort()
        choices = [me] + [t for t in self._sorted_runnable()
                          if t is not me]
        choices.sort(key=lambda t: t.tid)
        nxt = self.policy.pick(self, choices)
        self._record(nxt, op)
        if nxt is me:
            return
        me.state = "runnable"
        nxt.state = "running"
        self.current = nxt
        nxt.gate.release()
        self._park(me)

    def block(self, kind: str, obj, timeout: Optional[float] = None
              ) -> str:
        """Block the running task; returns 'signal' or 'timeout'."""
        me = self.me()
        if me is None:
            raise RuntimeError(
                "unmanaged thread hit a model-checked blocking op")
        if self.aborting:
            raise _Abort()
        me.state = "blocked"
        me.block_kind = kind
        me.block_obj = obj
        me.wake_reason = ""
        me.deadline = (None if timeout is None
                       else self.vclock + max(timeout, 0.0))
        self._grant_next(f"block:{kind}")
        self._park(me)
        me.deadline = None
        me.block_kind = ""
        me.block_obj = None
        return me.wake_reason or "signal"

    def wake(self, task: _Task, reason: str = "signal") -> None:
        """Make a blocked task runnable (does NOT transfer control)."""
        if task.state == "blocked":
            task.state = "runnable"
            task.wake_reason = reason

    def wake_waiters(self, kind: str, obj) -> None:
        for t in self.tasks.values():
            if (t.state == "blocked" and t.block_kind == kind
                    and t.block_obj is obj):
                self.wake(t)

    def _park(self, me: _Task) -> None:
        if not me.gate.acquire(timeout=_GATE_STALL_S):
            self._begin_abort(CheckFailure(
                f"scheduler stalled: task {me.name} never granted"))
            raise _Abort()
        if self.aborting:
            raise _Abort()
        # granter already set our state/current

    def _grant_next(self, op: str) -> None:
        """Hand control to some runnable task; fire virtual deadlines
        when idle; detect deadlock. Runs on the ceding thread."""
        while True:
            runnable = self._sorted_runnable()
            if runnable:
                nxt = self.policy.pick(self, runnable)
                self._record(nxt, op)
                nxt.state = "running"
                self.current = nxt
                nxt.gate.release()
                return
            # idle: earliest virtual deadline fires (timed waiter or
            # timer); timeouts never preempt runnable threads
            cands = []
            for t in self.tasks.values():
                if t.state == "blocked" and t.deadline is not None:
                    cands.append((t.deadline, 0, t.tid, t))
            for tm in self.timers:
                cands.append((tm.mc_deadline, 1, tm.mc_seq, tm))
            if not cands:
                blocked = [f"{t.name}@{t.block_kind}"
                           for t in self.tasks.values()
                           if t.state == "blocked"]
                self._begin_abort(DeadlockError(
                    "deadlock: all live threads blocked "
                    f"({', '.join(sorted(blocked)) or 'none'}) with no "
                    "timed waiter or pending timer"))
                raise _Abort()
            cands.sort(key=lambda c: c[:3])
            deadline, kind, _seq, obj = cands[0]
            self.vclock = max(self.vclock, deadline)
            if kind == 0:
                obj.state = "runnable"
                obj.wake_reason = "timeout"
            else:
                self.timers.remove(obj)
                obj._mc_fire()           # registers a runnable task
            # loop: grant whoever is now runnable

    def on_thread_exit(self, task: _Task) -> None:
        task.state = "finished"
        if self.aborting:
            self._abort_release_all()
            return
        # wake joiners
        self.wake_waiters("join", task)
        if any(t.state != "finished" for t in self.tasks.values()):
            try:
                self._grant_next("exit")
            except _Abort:
                pass

    # -- failure handling ---------------------------------------------

    def _begin_abort(self, exc: BaseException) -> None:
        with self._abort_mu:
            if self.error is None:
                self.error = exc
            self.aborting = True
        # wake every parked task NOW so nobody waits out the gate
        # stall timeout; they observe `aborting` and unwind via _Abort
        self._abort_release_all()

    def record_error(self, exc: BaseException) -> None:
        self._begin_abort(exc)

    def _abort_release_all(self) -> None:
        me = self.me()
        with self._abort_mu:
            victims = [t for t in self.tasks.values()
                       if t.state != "finished" and not t.abort_granted
                       and t is not me]
            for t in victims:
                t.abort_granted = True
        for t in victims:
            t.gate.release()

    def finalize_abort(self) -> None:
        """Driver-side cleanup: release every parked task so it unwinds
        via _Abort, then join the real threads."""
        self._abort_release_all()
        deadline = _REAL_TIME() + 10.0
        for t in self.tasks.values():
            if t.thread is not None and t.state != "finished":
                t.thread.join(timeout=max(0.1, deadline - _REAL_TIME()))


# --------------------------------------------------------------------
# cooperative primitives
# --------------------------------------------------------------------

_NAME_SEQ = [0]


def _mc_name(prefix: str) -> str:
    _NAME_SEQ[0] += 1
    return f"{prefix}{_NAME_SEQ[0]}"


def _sch_task():
    sch = _ACTIVE
    if sch is None:
        return None, None
    return sch, sch.me()


class MCLock:
    _reentrant = False

    def __init__(self):
        self._mc_name = _mc_name("L")
        self.owner: Optional[_Task] = None
        self.count = 0
        self._fallback = _thread.allocate_lock()   # unmanaged callers

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        sch, me = _sch_task()
        if sch is None or me is None:
            if timeout is not None and timeout >= 0:
                return self._fallback.acquire(blocking, timeout)
            return self._fallback.acquire(blocking)
        if sch.aborting:
            return True
        sch.switch(f"acq:{self._mc_name}")
        if self.owner is me:
            if self._reentrant:
                self.count += 1
                return True
            raise RuntimeError(
                f"non-reentrant lock {self._mc_name} re-acquired")
        deadline = (None if timeout is None or timeout < 0
                    else sch.vclock + timeout)
        while self.owner is not None:
            if not blocking:
                return False
            remaining = (None if deadline is None
                         else deadline - sch.vclock)
            if remaining is not None and remaining <= 0:
                return False
            reason = sch.block("lock", self, remaining)
            if reason == "timeout" and self.owner is not None:
                return False
        self.owner = me
        self.count = 1
        return True

    def release(self) -> None:
        sch, me = _sch_task()
        if sch is None or me is None:
            try:
                self._fallback.release()
            except RuntimeError:
                pass
            return
        if sch.aborting:
            return
        if self.owner is not me:
            raise RuntimeError(f"release of un-owned {self._mc_name}")
        self.count -= 1
        if self.count > 0:
            return
        self.owner = None
        sch.wake_waiters("lock", self)
        sch.switch(f"rel:{self._mc_name}")

    def locked(self) -> bool:
        return self.owner is not None

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # condvar support: fully release / restore (RLock depth)
    def _mc_release_save(self, me: _Task) -> int:
        saved = self.count
        self.count = 0
        self.owner = None
        sch = _ACTIVE
        if sch is not None:
            sch.wake_waiters("lock", self)
        return saved

    def _mc_acquire_restore(self, saved: int) -> None:
        self.acquire()
        self.count = saved


class MCRLock(MCLock):
    _reentrant = True


class MCCondition:
    def __init__(self, lock=None):
        self._mc_name = _mc_name("C")
        self._lock = lock if lock is not None else MCRLock()
        self.waiters: List[_Task] = []

    def __enter__(self):
        self._lock.acquire()
        return self

    def __exit__(self, *exc):
        self._lock.release()
        return False

    def acquire(self, *a, **kw):
        return self._lock.acquire(*a, **kw)

    def release(self):
        return self._lock.release()

    def _check_owned(self, sch, me) -> bool:
        owner = getattr(self._lock, "owner", None)
        if owner is not me:
            if sch.aborting:
                return False
            raise RuntimeError(
                f"condvar {self._mc_name} op without its lock held")
        return True

    def wait(self, timeout: Optional[float] = None) -> bool:
        sch, me = _sch_task()
        if sch is None or me is None:
            raise RuntimeError(
                "unmanaged thread waited on a model-checked condvar")
        if sch.aborting:
            raise _Abort()
        if not self._check_owned(sch, me):
            return False
        self.waiters.append(me)
        saved = self._lock._mc_release_save(me)
        try:
            reason = sch.block("cond", self, timeout)
        finally:
            if me in self.waiters:
                self.waiters.remove(me)
        self._lock._mc_acquire_restore(saved)
        return reason == "signal"

    def wait_for(self, predicate, timeout: Optional[float] = None):
        sch = _ACTIVE
        endtime = None
        if timeout is not None and sch is not None:
            endtime = sch.vclock + timeout
        result = predicate()
        while not result:
            waittime = None
            if endtime is not None and sch is not None:
                waittime = endtime - sch.vclock
                if waittime <= 0:
                    break
            self.wait(waittime)
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        sch, me = _sch_task()
        if sch is None or me is None or sch.aborting:
            return
        if not self._check_owned(sch, me):
            return
        woken = self.waiters[:n]
        del self.waiters[:n]
        for t in woken:
            sch.wake(t)                  # they re-contend for the lock
        sch.switch(f"notify:{self._mc_name}")

    def notify_all(self) -> None:
        self.notify(len(self.waiters))


class MCEvent:
    def __init__(self):
        self._cond = MCCondition(MCLock())
        self._flag = False

    def is_set(self) -> bool:
        return self._flag

    def set(self) -> None:
        sch, me = _sch_task()
        if sch is None or me is None:
            self._flag = True
            return
        with self._cond:
            self._flag = True
            self._cond.notify_all()

    def clear(self) -> None:
        self._flag = False

    def wait(self, timeout: Optional[float] = None) -> bool:
        sch, me = _sch_task()
        if sch is None or me is None:
            deadline = (None if timeout is None
                        else _REAL_MONOTONIC() + timeout)
            while not self._flag:
                if deadline is not None and _REAL_MONOTONIC() >= deadline:
                    break
                _REAL_SLEEP(0.005)
            return self._flag
        deadline = (None if timeout is None
                    else sch.vclock + max(timeout, 0.0))
        with self._cond:
            while not self._flag:
                remaining = (None if deadline is None
                             else deadline - sch.vclock)
                if remaining is not None and remaining <= 0:
                    break
                self._cond.wait(remaining)
            return self._flag


class _StartedStub:
    """Replaces Thread._started under the checker: the real bootstrap
    sets it from UNMANAGED code at an uncontrolled real-time point, and
    Thread.start() blocks on it — a nondeterministic handoff. Under the
    checker the child's first user instruction is gated by the task
    gate instead, so start() must never wait on the bootstrap."""

    def __init__(self):
        self._flag = False

    def set(self) -> None:
        self._flag = True

    def is_set(self) -> bool:
        return self._flag

    def wait(self, timeout=None) -> bool:
        return True                      # never block on the bootstrap


class MCThread(_REAL_THREAD):
    def start(self) -> None:
        sch = _ACTIVE
        if sch is None:
            _REAL_THREAD.start(self)
            return
        me = sch.me()
        if me is None:
            _REAL_THREAD.start(self)
            return
        self._mc_task = sch._new_task(self.name or "thread", self)
        self._mc_sch = sch    # the OS thread may first run after the
        self._started = _StartedStub()          # type: ignore
        _REAL_THREAD.start(self)              # window closed (leaks)
        sch.switch(f"start:{self._mc_task.name}")

    def run(self) -> None:
        task = getattr(self, "_mc_task", None)
        if task is None:
            _REAL_THREAD.run(self)
            return
        sch = self._mc_sch
        sch.idents[threading.get_ident()] = task
        try:
            if not task.gate.acquire(timeout=_GATE_STALL_S):
                return
            if sch.aborting:
                return
            try:
                _REAL_THREAD.run(self)
            except _Abort:
                pass
            except BaseException as e:   # a finding: surface it
                sch.record_error(e)
        finally:
            sch.idents.pop(threading.get_ident(), None)
            sch.on_thread_exit(task)

    def join(self, timeout: Optional[float] = None) -> None:
        task = getattr(self, "_mc_task", None)
        sch = _ACTIVE
        if task is None or sch is None or sch.me() is None:
            _REAL_THREAD.join(self, timeout)
            return
        if sch.aborting:
            return
        deadline = (None if timeout is None
                    else sch.vclock + max(timeout, 0.0))
        while task.state != "finished":
            remaining = (None if deadline is None
                         else deadline - sch.vclock)
            if remaining is not None and remaining <= 0:
                return
            reason = sch.block("join", task, remaining)
            if reason == "timeout":
                return

    def is_alive(self) -> bool:
        task = getattr(self, "_mc_task", None)
        if task is None:
            return _REAL_THREAD.is_alive(self)
        return task.state != "finished"


class MCTimer:
    """threading.Timer stand-in with NO OS thread while pending: the
    scheduler fires it (spawning a managed thread) when the system is
    idle and its virtual deadline is earliest."""

    _seq = [0]

    def __init__(self, interval, function, args=None, kwargs=None):
        self.interval = interval
        self.function = function
        self.args = args if args is not None else []
        self.kwargs = kwargs if kwargs is not None else {}
        self.daemon = True
        self.name = _mc_name("timer-")
        self.mc_deadline = 0.0
        MCTimer._seq[0] += 1
        self.mc_seq = MCTimer._seq[0]
        self._cancelled = False
        self._thread: Optional[MCThread] = None

    def start(self) -> None:
        sch = _ACTIVE
        if sch is None or sch.me() is None:
            t = _REAL_THREAD(target=self._real_fire, daemon=True)
            self._thread = t             # degraded mode, off-scenario
            t.start()
            return
        self.mc_deadline = sch.vclock + max(self.interval, 0.0)
        sch.timers.append(self)

    def _real_fire(self):
        _REAL_SLEEP(self.interval)
        if not self._cancelled:
            self.function(*self.args, **self.kwargs)

    def _mc_fire(self) -> None:
        if self._cancelled:
            return
        t = MCThread(target=self.function, args=self.args,
                     kwargs=self.kwargs, name=self.name, daemon=True)
        self._thread = t
        t.start()

    def cancel(self) -> None:
        self._cancelled = True
        sch = _ACTIVE
        if sch is not None and self in sch.timers:
            sch.timers.remove(self)

    def is_alive(self) -> bool:
        sch = _ACTIVE
        if sch is not None and self in sch.timers:
            return True
        return self._thread is not None and self._thread.is_alive()

    def join(self, timeout=None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)


# --------------------------------------------------------------------
# the patch window
# --------------------------------------------------------------------

def _mc_time() -> float:
    sch, me = _sch_task()
    if sch is None or me is None:
        return _REAL_TIME()
    return sch.vclock


def _mc_monotonic() -> float:
    sch, me = _sch_task()
    if sch is None or me is None:
        return _REAL_MONOTONIC()
    return sch.vclock


def _mc_sleep(seconds: float) -> None:
    sch, me = _sch_task()
    if sch is None or me is None:
        _REAL_SLEEP(seconds)
        return
    sch.block("sleep", None, max(seconds, 0.0))


class _PatchWindow:
    """Swap the threading/time primitives for their cooperative
    versions, suspend the nomadsan runtime (its TLS locksets don't see
    MC locks and would report false violations), seed the global PRNG
    (RaftNode election jitter consults it), and restore EVERYTHING on
    exit — including whatever factories nomadsan had installed."""

    def __init__(self, scheduler: Scheduler, seed: int):
        self.scheduler = scheduler
        self.seed = seed
        self._saved: dict = {}
        self._san_active = False
        self._rng_state = None

    def __enter__(self):
        global _ACTIVE
        if _ACTIVE is not None:
            raise RuntimeError("model-check scenarios cannot nest")
        self._saved = {
            "Thread": threading.Thread, "Timer": threading.Timer,
            "Lock": threading.Lock, "RLock": threading.RLock,
            "Condition": threading.Condition, "Event": threading.Event,
            "time": time.time, "monotonic": time.monotonic,
            "sleep": time.sleep,
        }
        from . import sanitizer
        self._san_active = sanitizer.GLOBAL.active
        sanitizer.GLOBAL.active = False
        self._rng_state = random.getstate()
        random.seed(0x6D6F6463 ^ self.seed)
        threading.Thread = MCThread                 # type: ignore
        threading.Timer = MCTimer                   # type: ignore
        threading.Lock = MCLock                     # type: ignore
        threading.RLock = MCRLock                   # type: ignore
        threading.Condition = MCCondition           # type: ignore
        threading.Event = MCEvent                   # type: ignore
        time.time = _mc_time                        # type: ignore
        time.monotonic = _mc_monotonic              # type: ignore
        time.sleep = _mc_sleep                      # type: ignore
        _ACTIVE = self.scheduler
        return self

    def __exit__(self, *exc):
        global _ACTIVE
        _ACTIVE = None
        threading.Thread = self._saved["Thread"]    # type: ignore
        threading.Timer = self._saved["Timer"]      # type: ignore
        threading.Lock = self._saved["Lock"]        # type: ignore
        threading.RLock = self._saved["RLock"]      # type: ignore
        threading.Condition = self._saved["Condition"]  # type: ignore
        threading.Event = self._saved["Event"]      # type: ignore
        time.time = self._saved["time"]             # type: ignore
        time.monotonic = self._saved["monotonic"]   # type: ignore
        time.sleep = self._saved["sleep"]           # type: ignore
        from . import sanitizer
        sanitizer.GLOBAL.active = self._san_active
        random.setstate(self._rng_state)
        return False


# --------------------------------------------------------------------
# scenario driver
# --------------------------------------------------------------------

@dataclass
class ScenarioEnv:
    seed: int
    fsfaults: bool = False


SCENARIOS: Dict[str, Callable[[ScenarioEnv], None]] = {}


def scenario(name: str):
    def register(fn):
        SCENARIOS[name] = fn
        return fn
    return register


_JAX_COMPILE_PATH_WARM = False


def _preload() -> None:
    """Import every module the scenarios touch BEFORE the patch window:
    module-level locks (logging, concurrent.futures internals) must be
    real OS primitives, and lazy imports inside the window would see
    the patched threading module."""
    import concurrent.futures
    import concurrent.futures.thread  # noqa: F401  (lazy in 3.8+)
    import queue  # noqa: F401
    import tempfile  # noqa: F401

    from ..chaos import fsfaults, invariants  # noqa: F401
    from ..core import broker, events, heartbeat, loadctl, metrics, plan_apply  # noqa: F401
    from ..utils import backoff  # noqa: F401
    from ..obs import trace  # noqa: F401
    from ..raft import durable, fsm, node, transport  # noqa: F401
    from ..state import persist, store, watch  # noqa: F401
    from ..structs import alloc, evaluation, node  # noqa: F401
    from ..tensor import jit_guard, placer  # noqa: F401  (module locks)
    from . import launch_ledger, ownership, shadow  # noqa: F401

    # jax imports big chunks of its compile path lazily on the FIRST
    # compile (jax._src.compilation_cache among them, whose module-level
    # _cache_initialized_mutex would otherwise be born inside the patch
    # window as a cooperative lock and deadlock against XLA's own C++
    # compile serialization). One throwaway compile here forces every
    # lazy import and lock on that path into existence as real OS
    # primitives; per-process, so repeat runs pay nothing.
    global _JAX_COMPILE_PATH_WARM
    if not _JAX_COMPILE_PATH_WARM:
        import jax
        import numpy as np

        from jax._src import compilation_cache  # noqa: F401
        jax.jit(lambda a: a + 0.0)(np.float32(0.0)).block_until_ready()
        _JAX_COMPILE_PATH_WARM = True
    assert concurrent.futures.ThreadPoolExecutor is not None


def run_scenario(name: str, seed: int, policy: str = "random",
                 max_steps: int = 50_000,
                 fsfaults: bool = False) -> CheckResult:
    """One scenario under one seeded schedule. Deterministic: the same
    (name, seed, policy) triple replays the same trace and outcome."""
    _preload()
    fn = SCENARIOS.get(name)
    if fn is None:
        raise KeyError(f"unknown scenario {name!r}; "
                       f"have {sorted(SCENARIOS)}")
    pol = POLICIES[policy](seed)
    _NAME_SEQ[0] = 0                     # trace names restart per run
    MCTimer._seq[0] = 0
    from ..obs import RECORDER
    RECORDER.clear()                     # per-run forensics isolation
    sch = Scheduler(pol, max_steps=max_steps)
    env = ScenarioEnv(seed=seed, fsfaults=fsfaults)
    leaked: List[str] = []
    with _PatchWindow(sch, seed):
        main = sch.register_main()
        try:
            fn(env)
            live = [t.name for t in sch.tasks.values()
                    if t is not main and t.state != "finished"]
            if live:
                leaked = sorted(live)
                raise ThreadLeakError(
                    f"threads still alive at scenario end: {leaked}")
        except _Abort:
            pass
        except BaseException as e:
            sch.record_error(e)
        finally:
            main.state = "finished"
            sch.finalize_abort()
    err = sch.error
    error = None
    if err is not None:
        error = f"{err}"
        # attach the flight recorder to the finding: the subsystem
        # transitions leading up to the failure, under this exact
        # deterministic schedule
        dump = RECORDER.dump_text(last=40)
        if dump:
            error += "\n  flight recorder (last 40 events):\n" + dump
    return CheckResult(
        scenario=name, seed=seed, policy=pol.name, steps=sch.step,
        trace=sch.trace, leaked=leaked,
        error=error,
        error_type="" if err is None else type(err).__name__)


def explore(name: str, seeds, policies=("random", "pbound"),
            max_steps: int = 50_000, fsfaults: bool = False,
            stop_on_failure: bool = True) -> List[CheckResult]:
    """Sweep a scenario over seeds × policies; returns every result
    (failures first if stop_on_failure ended the sweep early)."""
    results: List[CheckResult] = []
    for s in seeds:
        for p in policies:
            r = run_scenario(name, s, policy=p, max_steps=max_steps,
                             fsfaults=fsfaults)
            results.append(r)
            if not r.ok and stop_on_failure:
                return results
    return results


def seed_from_env(default: int = 0) -> int:
    import os
    raw = os.environ.get("NOMAD_TPU_CHECK_SEED", "")
    if raw:
        try:
            return int(raw, 0)
        except ValueError:
            pass
    return default


# --------------------------------------------------------------------
# scenarios
# --------------------------------------------------------------------

class _FakeServer:
    """Just enough server for chaos.InvariantChecker's raft checks."""

    def __init__(self, raft):
        self.id = raft.id
        self.raft = raft
        self.crashed = False


class _FakeCluster:
    def __init__(self, nodes):
        self.servers = {n.id: _FakeServer(n) for n in nodes}


def _force_leader(node, term: int = 1) -> None:
    with node._lock:
        node.current_term = term
        node._become_leader_locked()


@scenario("raft_commit")
def _scenario_raft_commit(env: ScenarioEnv) -> None:
    """A 3-node raft cluster (log-writer + per-peer replicators on the
    leader) commits two proposers' batches; chaos invariants hold on
    every schedule. With env.fsfaults, one EIO is torn into a durable
    batch append mid-schedule (the chaos FSFaults shim): the poisoned
    batch must fail loudly and every invariant still hold."""
    import contextlib
    import errno as _errno
    import os
    import shutil
    import tempfile

    from ..chaos.fsfaults import FSFaults
    from ..chaos.invariants import InvariantChecker
    from ..raft.durable import DurableLog
    from ..raft.node import NotLeaderError, RaftNode
    from ..raft.transport import InProcTransport

    tmp = tempfile.mkdtemp(prefix="nomadcheck-") if env.fsfaults else None
    transport = InProcTransport()
    applied = {nid: [] for nid in ("a", "b", "c")}
    nodes = []
    try:
        for nid in ("a", "b", "c"):
            log = None
            if tmp:
                os.makedirs(f"{tmp}/{nid}", exist_ok=True)
                log = DurableLog(f"{tmp}/{nid}", fsync=False)
            nodes.append(RaftNode(
                nid, [p for p in ("a", "b", "c") if p != nid],
                transport, applied[nid].append,
                election_timeout=1e6,      # no spontaneous elections
                heartbeat_interval=0.05, log=log, batch=True))
        for n in nodes:
            n.start()
        _force_leader(nodes[0])
        shim = FSFaults() if env.fsfaults else None
        ctx = shim.installed() if shim else contextlib.nullcontext()
        with ctx:
            if shim:
                # torn batch append mid-schedule: the first durable
                # batch append on the leader dies with EIO
                shim.arm("log_append", errno_=_errno.EIO, count=1,
                         path_substr="/a/")
            errors: List[str] = []

            def propose(tag: str) -> None:
                for i in range(3):
                    try:
                        prop = nodes[0].apply_async((f"{tag}{i}",))
                        nodes[0].apply_wait(prop, timeout=30.0)
                    except (OSError, NotLeaderError, TimeoutError) as e:
                        if shim is None:
                            errors.append(f"{tag}{i}: {e!r}")

            t1 = threading.Thread(target=propose, args=("x",),
                                  name="proposer-x")
            t2 = threading.Thread(target=propose, args=("y",),
                                  name="proposer-y")
            t1.start()
            t2.start()
            t1.join()
            t2.join()
            if errors:
                raise AssertionError(
                    f"fault-free proposals failed: {errors}")
        checker = InvariantChecker()
        cluster = _FakeCluster(nodes)
        checker.check_election_safety(cluster)
        checker.check_log_matching(cluster)
        checker.check_committed_durability(cluster)
        if not env.fsfaults and nodes[0].commit_index < 6:
            raise AssertionError(
                f"leader committed {nodes[0].commit_index} < 6")
    finally:
        for n in nodes:
            n.stop()
        transport.close()
        if tmp:
            shutil.rmtree(tmp, ignore_errors=True)


@scenario("raft_stepdown")
def _scenario_raft_stepdown(env: ScenarioEnv) -> None:
    """change_config waits for a commit that can never happen (both
    peers unreachable) while a higher-term append_entries steps the
    leader down: the waiter must fail promptly with NotLeaderError —
    not burn its whole timeout (the change_config fix this PR)."""
    from ..raft.node import NotLeaderError, RaftNode
    from ..raft.transport import InProcTransport

    transport = InProcTransport()
    node = RaftNode("a", ["b", "c"], transport, lambda cmd: None,
                    election_timeout=1e6, heartbeat_interval=0.05,
                    batch=True)
    transport.partition("b")       # peers exist but never answer
    transport.partition("c")
    node.start()
    try:
        _force_leader(node)
        outcome: List[str] = []

        def change() -> None:
            try:
                node.add_server("d", timeout=30.0)
                outcome.append("committed")
            except NotLeaderError:
                outcome.append("not-leader")
            except TimeoutError:
                outcome.append("timeout")

        t = threading.Thread(target=change, name="config-changer")
        t.start()
        time.sleep(0.2)            # virtual: let the change register
        node.handle({"kind": "append_entries", "term": 9, "leader": "b",
                     "prev_log_index": 0, "prev_log_term": 0,
                     "entries": [], "leader_commit": 0})
        t.join()
        if outcome != ["not-leader"]:
            raise AssertionError(
                "config change through a step-down must fail fast with "
                f"NotLeaderError; got {outcome}")
    finally:
        node.stop()
        transport.close()


@scenario("read_index")
def _scenario_read_index(env: ScenarioEnv) -> None:
    """Read-path safety under adversarial schedules (the follower-read
    PR). Two independent hazards in one scenario:

    (1) Lease safety — a deposed leader holding a (lapsed) lease must
    never serve a read index: after the old leader is partitioned and a
    newer leader commits a write, read_index() on the old leader must
    raise NotLeaderError (its lease expired, its confirmation round
    cannot reach a quorum). Returning an index there would let a client
    read state that misses the new leader's committed write.

    (2) Waiter-table race — a blocking query whose deadline fires in
    the same window as the commit that satisfies it must either wake
    with the committed index or time out cleanly; the parked entry must
    never be lost or leak (WatchTable settles the race under its lock).
    """
    from ..raft.node import NotLeaderError, RaftNode
    from ..raft.transport import InProcTransport
    from ..state.store import StateStore

    # -- (1) lease safety across a silent deposition --
    transport = InProcTransport()
    nodes = {}
    for nid in ("a", "b", "c"):
        nodes[nid] = RaftNode(
            nid, [p for p in ("a", "b", "c") if p != nid],
            transport, lambda cmd: None,
            election_timeout=1e6,      # no spontaneous elections
            heartbeat_interval=0.05, batch=True,
            lease_duration=0.01)       # lapses within one sleep below
    try:
        for n in nodes.values():
            n.start()
        _force_leader(nodes["a"])
        # a quorum-committed write under A (also commits A's barrier)
        prop = nodes["a"].apply_async(("w1",))
        nodes["a"].apply_wait(prop, timeout=30.0)
        idx1 = nodes["a"].read_index(timeout=5.0)
        if idx1 < 1:
            raise AssertionError(f"connected leader read index {idx1}")
        # cut A off; let any held lease lapse, then depose it silently
        transport.partition("a")
        time.sleep(0.2)
        _force_leader(nodes["b"], term=2)
        prop = nodes["b"].apply_async(("w2",))
        nodes["b"].apply_wait(prop, timeout=30.0)  # b+c quorum commits
        try:
            stale = nodes["a"].read_index(timeout=0.5)
        except (NotLeaderError, TimeoutError):
            stale = None
        if stale is not None:
            raise AssertionError(
                f"deposed leader served read index {stale} while the new "
                f"leader committed through {nodes['b'].commit_index}")
    finally:
        for n in nodes.values():
            n.stop()
        transport.close()

    # -- (2) waiter-table commit/deadline race --
    store = StateStore()
    results: List[tuple] = []

    def waiter() -> None:
        results.append(store.watches.wait_min_index(1, timeout=0.05))

    def committer() -> None:
        time.sleep(0.05)           # lands right on the waiter deadline
        with store._write_lock:
            gen, _ = store._begin()
            store._commit(gen, [])

    t1 = threading.Thread(target=waiter, name="block-waiter")
    t2 = threading.Thread(target=committer, name="committer")
    t1.start()
    t2.start()
    t1.join()
    t2.join()
    idx, wake_ts = results[0]
    if wake_ts is not None and idx < 1:
        raise AssertionError(
            f"woken waiter observed index {idx} below its threshold")
    if idx not in (0, 1):
        raise AssertionError(f"impossible observed index {idx}")
    if store.watches.parked() != 0:
        raise AssertionError(
            f"waiter leaked: parked={store.watches.parked()}")
    # liveness after the race: a fresh waiter still wakes
    results.clear()
    t3 = threading.Thread(target=lambda: results.append(
        store.watches.wait_min_index(2, timeout=10.0)), name="waiter-2")
    t3.start()
    time.sleep(0.05)
    with store._write_lock:
        gen, _ = store._begin()
        store._commit(gen, [])
    t3.join()
    if results[0][0] < 2:
        raise AssertionError(f"post-race waiter saw {results[0]}")


@scenario("snapshot_compact")
def _scenario_snapshot_compact(env: ScenarioEnv) -> None:
    """Off-lock snapshot capture interleaved with concurrent applies
    and an incoming chunked install_snapshot. A partitioned follower
    forces the leader's async snapshot worker to compact past the
    follower's next index; on heal the leader streams a chunked
    install while proposals keep committing, and the freshly installed
    follower then runs its own off-lock capture. Invariants checked on
    every save/compact under the schedule: a locally captured
    snapshot's index never exceeds the node's last_applied at save
    time, and the log base never passes an index no saved snapshot
    covers."""
    import os
    import shutil
    import tempfile

    from ..chaos.invariants import InvariantChecker
    from ..raft.durable import DurableLog, SnapshotStore
    from ..raft.node import NotLeaderError, RaftNode
    from ..raft.transport import InProcTransport

    tmp = tempfile.mkdtemp(prefix="nomadcheck-snap-")
    transport = InProcTransport()
    violations: List[str] = []
    applied = {nid: [] for nid in ("a", "b", "c")}
    nodes: list = []

    class AuditSnapshots(SnapshotStore):
        """only_if_newer=True is unique to the async capture worker, so
        gate the capture invariant on it (installs legitimately save an
        index ABOVE last_applied — disk before memory)."""

        def __init__(self, dir_path):
            super().__init__(dir_path)
            self.node = None

        def _save_text(self, index, text, only_if_newer):
            if (only_if_newer and self.node is not None
                    and index > self.node.last_applied):
                violations.append(
                    f"{self.node.id}: captured snapshot index {index} > "
                    f"last_applied {self.node.last_applied}")
            return super()._save_text(index, text, only_if_newer)

    class AuditLog(DurableLog):
        def __init__(self, dir_path, snaps):
            super().__init__(dir_path, fsync=False)
            self._snaps = snaps

        def _audit_base(self, what):
            if self.base_index > max(self._snaps.last_index, 0):
                violations.append(
                    f"{what}: log base {self.base_index} > snapshot "
                    f"index {self._snaps.last_index}")

        def compact(self, upto_index, upto_term):
            super().compact(upto_index, upto_term)
            self._audit_base("compact")

        def reset_to(self, index, term):
            super().reset_to(index, term)
            self._audit_base("reset_to")

    try:
        for nid in ("a", "b", "c"):
            os.makedirs(f"{tmp}/{nid}", exist_ok=True)
            snaps = AuditSnapshots(f"{tmp}/{nid}")
            alog = AuditLog(f"{tmp}/{nid}", snaps)
            lst = applied[nid]
            n = RaftNode(
                nid, [p for p in ("a", "b", "c") if p != nid],
                transport, lst.append,
                election_timeout=1e6,      # no spontaneous elections
                heartbeat_interval=0.05, log=alog, snapshots=snaps,
                fsm_restore=(lambda data, lst=lst: lst.__setitem__(
                    slice(None), [tuple(x) for x in data["items"]])),
                fsm_capture=(lambda lst=lst: list(lst)),
                fsm_serialize=(lambda cap: {"items": [list(c)
                                                      for c in cap]}),
                snapshot_threshold=3, batch=True,
                snapshot_chunk_bytes=64)   # force a multi-frame install
            snaps.node = n
            nodes.append(n)
        for n in nodes:
            n.start()
        transport.partition("c")
        _force_leader(nodes[0])
        errors: List[str] = []

        def propose(tag: str) -> None:
            for i in range(4):
                try:
                    prop = nodes[0].apply_async((f"{tag}{i}",))
                    nodes[0].apply_wait(prop, timeout=30.0)
                except (OSError, NotLeaderError, TimeoutError) as e:
                    errors.append(f"{tag}{i}: {e!r}")

        t1 = threading.Thread(target=propose, args=("x",),
                              name="proposer-x")
        t2 = threading.Thread(target=propose, args=("y",),
                              name="proposer-y")
        t1.start()
        t2.start()
        t1.join()
        t2.join()
        if errors:
            raise AssertionError(f"proposals failed: {errors}")
        # the async worker must compact the leader past the cut
        # follower's next index (1) to force the install path
        for _ in range(300):
            if nodes[0].log.base_index > 0 and not nodes[0]._snap_active:
                break
            time.sleep(0.05)
        if nodes[0].log.base_index <= 0:
            raise AssertionError("leader never compacted its log")
        transport.heal("c")
        # traffic keeps flowing while the chunked install streams
        t3 = threading.Thread(target=propose, args=("z",),
                              name="proposer-z")
        t3.start()
        t3.join()
        if errors:
            raise AssertionError(f"post-heal proposals failed: {errors}")
        target = nodes[0].last_applied
        for _ in range(600):
            with nodes[0]._lock:
                inflight = bool(nodes[0]._snap_inflight)
            if nodes[2].last_applied >= target and not inflight \
                    and not any(n._snap_active for n in nodes):
                break
            time.sleep(0.05)
        if nodes[2].last_applied < target:
            raise AssertionError(
                f"wiped-in follower stuck at {nodes[2].last_applied} "
                f"< {target}")
        if violations:
            raise AssertionError("; ".join(violations))
        checker = InvariantChecker()
        cluster = _FakeCluster(nodes)
        checker.check_election_safety(cluster)
        checker.check_log_matching(cluster)
        checker.check_committed_durability(cluster)
        # install restores the leader's prefix and replication extends
        # it in log order, so the follower's applied sequence must be a
        # prefix of the leader's
        la, lc = applied["a"], applied["c"]
        if lc != la[:len(lc)]:
            raise AssertionError(
                f"follower state diverged after install: {lc} vs {la}")
    finally:
        for n in nodes:
            n.stop()
        transport.close()
        shutil.rmtree(tmp, ignore_errors=True)


class _PipelineStore:
    """Minimal async-proposing store for the plan_pipeline scenario: a
    managed apply thread turns propose_async tokens into applied
    indices, like RaftStore over a group-commit node."""

    can_propose_async = True
    latest_index = 0

    def __init__(self):
        self._cond = threading.Condition()
        self._q: List[int] = []
        self._applied: set = set()
        self._next = 0
        self._closed = False
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="store-apply")

    def start(self):
        self._thread.start()

    def propose_async(self, method: str, payloads) -> int:
        with self._cond:
            if self._closed:
                raise RuntimeError("store stopped")
            self._next += 1
            self._q.append(self._next)
            self._cond.notify_all()
            return self._next

    def wait_applied(self, token: int, timeout: float = 30.0) -> int:
        deadline = time.time() + timeout
        with self._cond:
            while token not in self._applied and not self._closed:
                remaining = deadline - time.time()
                if remaining <= 0:
                    raise TimeoutError(f"apply of round {token}")
                self._cond.wait(remaining)
            if token in self._applied:
                self.latest_index = max(self.latest_index, token)
                return token
            raise RuntimeError("store stopped")

    def upsert_plan_results_batch(self, payloads) -> int:
        with self._cond:
            self._next += 1
            return self._next

    def upsert_plan_results(self, **kw) -> int:
        with self._cond:
            self._next += 1
            return self._next

    def _run(self):
        with self._cond:
            while not self._closed:
                while not self._q and not self._closed:
                    self._cond.wait(0.2)
                while self._q:
                    self._applied.add(self._q.pop(0))
                self._cond.notify_all()

    def stop(self):
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout=10.0)


@scenario("plan_pipeline")
def _scenario_plan_pipeline(env: ScenarioEnv) -> None:
    """PlanApplier proposer/reaper at COMMIT_PIPELINE_DEPTH with
    submitters racing stop(): every submitted future must resolve —
    success or RuntimeError — never strand until timeout (the
    stop()-drain fix this PR)."""
    from concurrent.futures import Future
    from concurrent.futures import TimeoutError as FutTimeout
    from ..core.plan_apply import PlanApplier, PlanQueue

    store = _PipelineStore()
    store.start()
    applier = PlanApplier(store, PlanQueue(), batch=True)
    applier.start()
    try:
        stranded: List[str] = []

        def submit(tag: str) -> None:
            for i in range(3):
                try:
                    fut: Future = applier.submit_eval_updates(
                        [{"id": f"{tag}{i}"}])
                except RuntimeError:
                    return               # applier already stopped: fine
                try:
                    fut.result(timeout=20.0)
                except (FutTimeout, TimeoutError):
                    stranded.append(f"{tag}{i}")
                    return
                except RuntimeError:
                    return               # failed at stop: answered, fine

        t1 = threading.Thread(target=submit, args=("u",),
                              name="submitter-u")
        t2 = threading.Thread(target=submit, args=("v",),
                              name="submitter-v")
        stopper = threading.Thread(target=applier.stop, name="stopper")
        t1.start()
        t2.start()
        stopper.start()
        t1.join()
        t2.join()
        stopper.join()
        if stranded:
            raise AssertionError(
                f"eval-update futures stranded across stop(): {stranded}")
    finally:
        applier.stop()
        store.stop()


@scenario("broker_batch")
def _scenario_broker_batch(env: ScenarioEnv) -> None:
    """EvalBroker dequeue_batch under concurrent enqueue/nack with an
    enable→disable→enable flip: at most one delay thread may survive
    the flip (the generation-counter fix this PR), every dequeued eval
    is acked or nacked exactly once, and everything shuts down."""
    from ..core.broker import EvalBroker
    from ..structs.evaluation import Evaluation

    broker = EvalBroker(nack_timeout=60.0)
    broker.set_enabled(True)
    try:
        # the racy flip: a delay thread parked in its timed wait from
        # before the disable must exit even though we re-enabled first
        broker.set_enabled(False)
        broker.set_enabled(True)
        sch = current_scheduler()
        for _ in range(60):
            if sch.alive_named("broker-delay") <= 1:
                break
            time.sleep(0.05)
        else:
            raise AssertionError(
                "two broker-delay threads alive after "
                "disable→enable flip (delay thread leaked)")

        def produce() -> None:
            for i in range(4):
                broker.enqueue(Evaluation(id=f"e{i}", job_id=f"j{i}",
                                          modify_index=i + 1))

        seen: List[str] = []
        seen_lock = threading.Lock()

        def consume(name: str) -> None:
            nacked = False
            while True:
                batch = broker.dequeue_batch(["service"], max_batch=4,
                                             timeout=1.0)
                if not batch:
                    with seen_lock:
                        if len(seen) >= 4:
                            return
                    continue
                for ev, token in batch:
                    if not nacked:
                        nacked = True    # exercise redelivery once
                        broker.nack(ev.id, token)
                        continue
                    broker.ack(ev.id, token)
                    with seen_lock:
                        seen.append(ev.id)

        prod = threading.Thread(target=produce, name="producer")
        c1 = threading.Thread(target=consume, args=("c1",),
                              name="consumer-1")
        c2 = threading.Thread(target=consume, args=("c2",),
                              name="consumer-2")
        prod.start()
        c1.start()
        c2.start()
        prod.join()
        c1.join()
        c2.join()
        if sorted(seen) != ["e0", "e1", "e2", "e3"]:
            raise AssertionError(f"acked set wrong: {sorted(seen)}")
    finally:
        broker.set_enabled(False)
        t = broker._delay_thread
        if t is not None:
            t.join(timeout=10.0)


@scenario("solve_batch")
def _scenario_solve_batch(env: ScenarioEnv) -> None:
    """BulkSolverService worker-batch rendezvous (the "tpu-solve" joint
    tier): two batched workers, each an open_batch(2) whose member
    evals race their first joint submit against the service thread's
    bounded launch hold, a third non-joint request that must never
    share a launch group with the joint tier, and a stop() racing the
    tail. Asserts: every member's future resolves (solved or
    failed-at-stop — never stranded), solved == launched, the
    joint/greedy grouping stays pure, and every confirmed solve closes
    its ledger entry (the plan-applier handshake)."""
    import numpy as np

    from ..tensor.solver import (BulkSolverService, _LedgerEntry,
                                 batch_member, open_batch)

    svc = BulkSolverService()
    launches: List[tuple] = []
    launches_lock = threading.Lock()

    class _Static:
        node_index = {"n0": 0}
        device_arrays: dict = {}

    static = _Static()

    def host_dispatch_group(rs):
        # host stub for the device dispatch: record the launch group
        # and hand back an inflight handle — the service pipelines the
        # FETCH (ledger + future resolution) exactly as it would a real
        # double-buffered device launch, so the checker explores the
        # deferred-resolution interleavings too
        with launches_lock:
            launches.append(tuple(sorted(bool(r.joint) for r in rs)))
        return types.SimpleNamespace(rs=rs)

    def host_fetch(inf, pipelined: bool = False) -> None:
        # host stub for the single device_get: same token/ledger/future
        # protocol as _fetch, no accelerator
        for r in inf.rs:
            with svc._lock:
                svc._token += 1
                r.token = svc._token
                svc._ledger[r.token] = _LedgerEntry(
                    static, np.array([0]), np.array([1]),
                    np.ones(2, np.float32), 0.0)
            r.future.set_result(np.zeros(8, np.int64))

    svc._dispatch_group = host_dispatch_group
    svc._fetch = host_fetch

    outcomes: List[str] = []
    out_lock = threading.Lock()

    def member(ctx, seed: int, joint: bool, reject: bool) -> None:
        with batch_member(ctx if joint else None):
            try:
                _counts, token = svc.solve(
                    static=static, feas_base=None, aff=None,
                    ask=np.ones(2), k=1, tg_count=1.0, seed=seed,
                    used_fn=lambda: None, joint=joint)
            except RuntimeError:
                with out_lock:
                    outcomes.append("failed")  # drained at stop: answered
                return
            svc.confirm(token, ["n0"] if reject else [])
            with out_lock:
                outcomes.append("solved")

    def worker(base: int) -> None:
        ctx = open_batch(2)
        ms = [threading.Thread(target=member,
                               args=(ctx, base + i, True, i == 0),
                               name=f"member-{base + i}")
              for i in range(2)]
        for m in ms:
            m.start()
        for m in ms:
            m.join()

    w1 = threading.Thread(target=worker, args=(0,), name="worker-0")
    w2 = threading.Thread(target=worker, args=(10,), name="worker-1")
    lone = threading.Thread(target=member, args=(None, 20, False, False),
                            name="greedy-lone")
    stopper = threading.Thread(target=svc.stop, name="stopper")
    w1.start()
    w2.start()
    lone.start()
    stopper.start()
    for t in (w1, w2, lone, stopper):
        t.join()
    svc.stop()

    if len(outcomes) != 5:
        raise AssertionError(f"member outcomes missing: {outcomes}")
    solved = outcomes.count("solved")
    launched = sum(len(group) for group in launches)
    if launched != solved:
        raise AssertionError(
            f"{launched} requests launched but {solved} futures "
            f"resolved with results")
    if any(len(set(group)) > 1 for group in launches):
        raise AssertionError(
            f"a launch group mixed joint and greedy requests: {launches}")
    with svc._lock:
        leaked = dict(svc._ledger)
    if leaked:
        raise AssertionError(
            f"{len(leaked)} ledger entr(ies) leaked past confirm: "
            f"{sorted(leaked)}")


@scenario("store_ownership")
def _scenario_store_ownership(env: ScenarioEnv) -> None:
    """nomadown integration: a proposer replicates eval upserts through
    FSM.apply and keeps mutating its own retained objects afterwards —
    legal ONLY because the FSM deep-copies every command before handing
    it to the store — while readers race snapshots and iteration
    against the writes. The ownership sanitizer must stay silent.

    tests/test_ownership.py replays this scenario at a pinned seed with
    the FSM's defensive deepcopy monkeypatched away: the store then
    shares the proposer's objects, the post-apply mutations rewrite
    MVCC history, and the same seed MUST fail — the historical
    propose-retain-alias bug, reproduced deterministically."""
    from ..raft.fsm import FSM
    from ..state.store import StateStore
    from ..structs.evaluation import Evaluation
    from . import ownership

    own = ownership.GLOBAL
    was_active = own.active
    if not was_active:
        ownership.install()
    base = len(own.violations)
    store = StateStore()
    fsm = FSM(store)
    try:
        def propose() -> None:
            for i in range(4):
                ev = Evaluation(id=f"own-e{i}", job_id=f"own-j{i}",
                                status="pending")
                fsm.apply(("upsert_evals", ([ev],), {"ts": float(i + 1)}))
                # the proposer's object is private — the FSM deep-copied
                # the command — so this must NOT trip the sanitizer
                ev.status = "complete"
                ev.modify_index = 999 + i

        def read(name: str) -> None:
            for _ in range(6):
                snap = store.snapshot()
                for ev in snap.evals():
                    if ev.status != "pending":
                        raise AssertionError(
                            f"{name} saw a store row mutated after "
                            f"insert: {ev.id} status={ev.status!r}")
                time.sleep(0)

        p = threading.Thread(target=propose, name="own-proposer")
        r1 = threading.Thread(target=read, args=("r1",),
                              name="own-reader-1")
        r2 = threading.Thread(target=read, args=("r2",),
                              name="own-reader-2")
        p.start()
        r1.start()
        r2.start()
        p.join()
        r1.join()
        r2.join()
        own.verify_all()
        fresh = own.violations[base:]
        if fresh:
            raise AssertionError(
                "ownership sanitizer tripped: " + fresh[0].render())
    finally:
        del own.violations[base:]
        if not was_active:
            ownership.uninstall()


@scenario("node_lifecycle")
def _scenario_node_lifecycle(env: ScenarioEnv) -> None:
    """The sharded HeartbeatManager under adversarial interleavings: a
    client heartbeating across its TTL, a remove() racing the expiry
    sweep, and a failover restore() with duplicate/ghost ids — all
    against the shard threads. Asserts: a removed node is NEVER marked
    down, a heartbeating node is marked down only after a real silence
    >= TTL since its last beat, restored ids expire exactly once each,
    and every entry in the expiry attribution log spans >= TTL."""
    from ..core.heartbeat import HeartbeatManager

    ttl = 1.0
    marks: List[tuple] = []            # (node_id, monotonic mark time)
    marks_lock = threading.Lock()

    class _HBServer:
        def mark_nodes_down(self, node_ids, reason=""):
            now = time.monotonic()
            with marks_lock:
                for nid in node_ids:
                    marks.append((nid, now))

        def mark_node_down(self, node_id, reason=""):
            self.mark_nodes_down([node_id], reason=reason)

    mgr = HeartbeatManager(_HBServer(), ttl=ttl, shards=2, expiry_rate=0.0)
    mgr.set_enabled(True)
    try:
        beat_times: List[float] = []

        def beater() -> None:
            for _ in range(6):
                mgr.reset("alive")
                beat_times.append(time.monotonic())
                time.sleep(ttl * 0.4)

        def remover() -> None:
            mgr.reset("removed")
            time.sleep(ttl * 0.3)
            mgr.remove("removed")

        def restorer() -> None:
            time.sleep(ttl * 0.2)
            if mgr.restore(["dup", "dup", "ghost", ""]) != 2:
                raise AssertionError("restore armed wrong timer count")

        threads = [threading.Thread(target=beater, name="hb-beater"),
                   threading.Thread(target=remover, name="hb-remover"),
                   threading.Thread(target=restorer, name="hb-restorer")]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # everything has gone silent now; give every armed timer (last
        # "alive" beat + restore grace) room to fire
        time.sleep(ttl * 3.0)

        with marks_lock:
            down = list(marks)
        by_id: Dict[str, List[float]] = {}
        for nid, at in down:
            by_id.setdefault(nid, []).append(at)
        if "removed" in by_id:
            raise AssertionError(
                "remove()d node was marked down anyway (lost-removal "
                "race with the expiry sweep)")
        for nid in ("alive", "dup", "ghost"):
            if len(by_id.get(nid, [])) != 1:
                raise AssertionError(
                    f"{nid!r} marked down {len(by_id.get(nid, []))} "
                    f"times, want exactly 1: {by_id}")
        if by_id["alive"][0] < beat_times[-1] + ttl * 0.95:
            raise AssertionError(
                f"'alive' expired {by_id['alive'][0] - beat_times[-1]:.3f}s "
                f"after its last beat — a missed-TTL false positive")
        for nid, armed_at, expired_at in mgr.expiry_snapshot():
            if expired_at - armed_at < ttl * 0.95:
                raise AssertionError(
                    f"attribution log shows {nid!r} expired only "
                    f"{expired_at - armed_at:.3f}s after arming")
        if mgr.active() != 0:
            raise AssertionError(
                f"{mgr.active()} timers still armed after the sweep")
    finally:
        mgr.set_enabled(False)


@scenario("tensor_launch")
def _scenario_tensor_launch(env: ScenarioEnv) -> None:
    """nomadjit integration: the main task cold-launches each shape
    through placer._warm_launch (the real launch driver), then two
    racing workers hammer the warmed shapes under adversarial
    interleavings. Cold compiles stay on the main task deliberately:
    XLA serializes concurrent compiles behind C++ mutexes the scheduler
    cannot see, so a parked cooperative task mid-compile would wedge a
    peer blocked in native code. Warm launches take jit's C++ cache-hit
    fast path and are safe to race. Asserts: the cold launch of each
    shape attributes >= 1 compile to its ledger window, warm windows
    record ZERO compiles and exactly one host sync each, a quiesced
    strict sweep reports no leaked windows, and the violation list
    stays empty. A final leg opens a deliberately warm-marked window
    around an uncompiled shape and asserts the warm-compile violation
    IS recorded (then scrubs it) — the detector must be live, not
    vacuously green."""
    import jax
    import numpy as np

    from ..tensor.placer import _warm_launch
    from . import launch_ledger

    ledger = launch_ledger.GLOBAL
    was_active = ledger.active
    if not was_active:
        launch_ledger.install()
    base = len(ledger.violations)
    tag = f"mc_launch_{env.seed}"

    def kernel(a):
        return a * 2.0 + 1.0

    f = jax.jit(kernel)
    f.__name__ = tag
    warm: set = set()
    shapes = [(4 + (env.seed % 3),), (9 + (env.seed % 3),)]
    errors: List[str] = []

    def launch(shape) -> object:
        dev = jax.device_put(np.ones(shape, np.float32))
        with _warm_launch(f, shape, warm):
            return jax.device_get(f(dev))

    def worker(name: str) -> None:
        try:
            for _ in range(3):
                for shape in shapes:
                    if launch(shape).shape != shape:
                        errors.append(f"{name}: bad launch result")
                    time.sleep(0)
        except Exception as e:  # surfaced after join
            errors.append(f"{name}: {type(e).__name__}: {e}")

    try:
        for shape in shapes:       # cold, main task only (see docstring)
            if launch(shape).shape != shape:
                raise AssertionError("bad cold launch result")
        if set(shapes) - warm:
            raise AssertionError(
                f"cold launches left shapes unwarmed: {set(shapes) - warm}")
        t1 = threading.Thread(target=worker, args=("w1",),
                              name="launch-w1")
        t2 = threading.Thread(target=worker, args=("w2",),
                              name="launch-w2")
        t1.start()
        t2.start()
        t1.join()
        t2.join()
        if errors:
            raise AssertionError("; ".join(errors))
        mine = [r for r in ledger.records if r.name == tag]
        if not mine:
            raise AssertionError("no ledger records for the launches")
        cold_compiles = sum(r.compiles for r in mine if not r.warm)
        if cold_compiles < len(shapes):
            raise AssertionError(
                f"cold launches attributed only {cold_compiles} "
                f"compile(s) for {len(shapes)} shapes — the compile "
                "listener is not feeding the ledger")
        for r in mine:
            if r.warm and r.compiles:
                raise AssertionError(
                    f"warm window {r.key!r} recorded {r.compiles} "
                    f"compile(s): {r.sites}")
            if r.gets != 1:
                raise AssertionError(
                    f"launch window {r.key!r} recorded {r.gets} host "
                    f"syncs, want exactly 1: {r.sites}")
        problems = ledger.verify_all(strict=True)
        fresh = ledger.violations[base:]
        if fresh or problems:
            raise AssertionError(
                "launch ledger tripped on a clean schedule: "
                + (fresh[0].render() if fresh else problems[0]))
        # negative leg: a warm-marked window around a cold shape MUST
        # record the warm-compile violation
        g = jax.jit(kernel)
        g.__name__ = tag + "_neg"
        dev = jax.device_put(np.ones((17,), np.float32))
        with ledger.window(g.__name__, key=(17,), warm=True):
            jax.device_get(g(dev))
        fresh = ledger.violations[base:]
        if not any(v.kind == "warm-compile" for v in fresh):
            raise AssertionError(
                "warm-compile detector is dead: a compile inside a "
                "warm-marked window recorded no violation")
    finally:
        del ledger.violations[base:]
        if not was_active:
            launch_ledger.uninstall()


@scenario("event_flow")
def _scenario_event_flow(env: ScenarioEnv) -> None:
    """nomadflow integration: a store + event broker with a shadow
    replica attached, driven by concurrent mutators covering every
    Allocation/Node/Evaluation delta kind — bulk upserts, client status
    updates (including terminal flips), eval churn with deletes, a
    terminal-alloc GC sweep, and an operator dump/restore that forces
    the full-ring truncation → resync path. After every writer joins,
    the replica's fingerprint compare against a fresh MVCC snapshot
    rebuild (usage columns included) must be exact: under ANY
    interleaving the event stream carries enough information to
    reconstruct the store, or a consumer somewhere is silently stale.

    tests/test_flow_rules.py replays this scenario at a pinned seed
    with a delta kind suppressed to prove the compare actually bites."""
    import numpy as np

    from ..core.events import EventBroker
    from ..state.persist import dump_store, restore_store
    from ..state.store import StateStore
    from ..structs.alloc import Allocation
    from ..structs.evaluation import Evaluation
    from ..structs.node import Node
    from . import shadow as shadow_mod

    store = StateStore()
    broker = EventBroker(store, ring_size=32, shards=2)
    tracker = shadow_mod.ShadowTracker(every=3)
    tracker.install()
    rep = tracker.attach(store, broker)

    def write_nodes() -> None:
        for i in range(4):
            store.upsert_node(Node(id=f"fn{i}"))
        # rewrite a node (same id, new status) — the upsert event must
        # carry the new row, not the old
        store.upsert_node(Node(id="fn0", status="down"))

    def write_evals() -> None:
        store.upsert_evals([Evaluation(id=f"fe{i}", job_id="fj")
                            for i in range(5)])
        store.delete_evals(["fe1", "fe3"])

    def write_allocs() -> None:
        allocs = []
        for i in range(6):
            a = Allocation(id=f"fa{i}", node_id=f"fn{i % 4}",
                           job_id="fj", eval_id="fe0")
            a.allocated_vec = np.full_like(a.allocated_vec,
                                           float(i + 1))
            allocs.append(a)
        store.upsert_allocs(allocs)
        # client flips two to terminal, then GC reaps the orphans
        # (no job row exists, so terminal allocs are collectable)
        for aid in ("fa1", "fa4"):
            upd = Allocation(id=aid, client_status="complete")
            store.update_allocs_from_client([upd])
        store.gc_terminal_allocs(before_index=store._index + 1)

    def restore_leg() -> None:
        # operator restore: the broker truncates every ring and the
        # replica must resync instead of patching a holey stream
        restore_store(store, dump_store(store))
        store.upsert_node(Node(id="fn-post-restore"))

    threads = [threading.Thread(target=write_nodes, name="flow-nodes"),
               threading.Thread(target=write_evals, name="flow-evals"),
               threading.Thread(target=write_allocs, name="flow-allocs"),
               threading.Thread(target=restore_leg, name="flow-restore")]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    msg = rep.force_compare()
    if msg is not None:
        raise AssertionError(f"shadow diverged: {msg}")
    if tracker.violations:
        raise AssertionError("shadow tracker tripped: "
                             + tracker.violations[0].render())


@scenario("overload")
def _scenario_overload(env: ScenarioEnv) -> None:
    """nomadload admission plane under racing callers on a virtual
    clock: three submitter threads hammer the gate while one flips the
    watermarked queue between calm and hard-tripped and another reads
    snapshot()/ledger() concurrently. Checked across every explored
    interleaving:

    - tier-0 is NEVER shed while alive (invariant 10's kernel);
    - accounting closes: admitted + shed == calls made, and the ledger
      agrees with the stats;
    - the shared RetryBudget can never hand out more retries than its
      cap + ratio * recorded requests (no interleaving over-spends);
    - RetryLater survives its wire str() round trip from inside a
      racing thread."""
    from ..core.loadctl import (
        TIER_LIVENESS,
        TIER_SUBMIT,
        AdmissionController,
        RetryLater,
    )
    from ..utils.backoff import RetryBudget

    clock = [0.0]
    clock_lock = threading.Lock()

    def now() -> float:
        with clock_lock:
            clock[0] += 0.001  # every observation advances virtual time
            return clock[0]

    depth = [0]
    adm = AdmissionController(enabled=True, clock=now, refresh_s=0.0,
                              brownout_after=0.05, brownout_exit=0.1)
    adm.register_queue("q", lambda: depth[0], soft=10, hard=100,
                       commit_path=True)
    budget = RetryBudget(ratio=0.25, min_rate=0.0, cap=3.0, clock=now)

    calls = [0]
    calls_lock = threading.Lock()
    errors: List[str] = []

    def submitter(name: str) -> None:
        for _ in range(8):
            budget.record_request()
            with calls_lock:
                calls[0] += 1
            after = adm.try_admit(TIER_SUBMIT, source=name)
            if after is not None:
                # shed: retry once iff the budget allows, as a real
                # client would; rehydrate the wire form on the way
                e = RetryLater(TIER_SUBMIT, after, reason=name)
                r = RetryLater("RetryLater: " + str(e))
                if abs(r.after - e.after) > 0.001 or r.tier != e.tier:
                    errors.append(f"wire roundtrip broke: {e} -> {r}")
                if budget.spend_retry():
                    with calls_lock:
                        calls[0] += 1
                    adm.try_admit(TIER_SUBMIT, source=name)

    def liveness() -> None:
        for _ in range(12):
            with calls_lock:
                calls[0] += 1
            if adm.try_admit(TIER_LIVENESS, source="hb") is not None:
                errors.append("tier-0 shed while alive")

    def flipper() -> None:
        for _ in range(6):
            depth[0] = 100
            now()
            adm.shed_floor()
            depth[0] = 0
            now()
            adm.shed_floor()

    def reader() -> None:
        for _ in range(6):
            snap = adm.snapshot()
            if snap["shed_floor"] < TIER_SUBMIT:
                errors.append(f"floor below submit: {snap}")
            adm.ledger()

    threads = [threading.Thread(target=submitter, args=(f"s{i}",),
                                name=f"submitter-{i}") for i in range(3)]
    threads.append(threading.Thread(target=liveness, name="liveness"))
    threads.append(threading.Thread(target=flipper, name="flipper"))
    threads.append(threading.Thread(target=reader, name="reader"))
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    if errors:
        raise AssertionError(f"overload scenario: {errors[:3]}")
    ledger = adm.ledger()
    shed_t0 = [e for e in ledger if e[1] == TIER_LIVENESS
               and e[2] == "shed"]
    if shed_t0:
        raise AssertionError(f"{len(shed_t0)} tier-0 sheds while alive")
    # every try_admit records exactly one outcome, in both the stats
    # and the ledger — no interleaving loses or double-counts one
    if adm.stats["admitted"] + adm.stats["shed"] != calls[0]:
        raise AssertionError(
            f"gate accounting leak: {calls[0]} calls vs "
            f"{adm.stats['admitted']} + {adm.stats['shed']} outcomes")
    if len(ledger) != calls[0]:
        raise AssertionError(
            f"ledger/stats disagree: {calls[0]} calls, "
            f"{len(ledger)} ledger entries")
    # the retry budget can never over-spend: every retry was funded by
    # the starting cap or a recorded request's deposit
    max_retries = budget.cap + budget.ratio * budget.stats["requests"]
    if budget.stats["retries"] > max_retries + 1e-9:
        raise AssertionError(
            f"retry budget over-spent: {budget.stats} (max "
            f"{max_retries:.2f})")


SMOKE_SCENARIOS = ("raft_commit", "raft_stepdown", "read_index",
                   "snapshot_compact",
                   "plan_pipeline", "broker_batch", "solve_batch",
                   "store_ownership", "node_lifecycle", "tensor_launch",
                   "event_flow", "overload")


def smoke(base_seed: int, seeds_per_scenario: int = 3,
          out=print) -> int:
    """The bounded check.sh gate: a few seeds per scenario per policy,
    plus one fsfaults-composed raft schedule. Returns count of
    failures."""
    failures = 0
    for name in SMOKE_SCENARIOS:
        results = explore(
            name, range(base_seed, base_seed + seeds_per_scenario))
        for r in results:
            if not r.ok:
                failures += 1
                out(r.render())
        ok = sum(1 for r in results if r.ok)
        out(f"  {name}: {ok}/{len(results)} schedules ok")
    r = run_scenario("raft_commit", base_seed, policy="random",
                     fsfaults=True)
    out(f"  raft_commit+fsfaults: "
        f"{'ok' if r.ok else 'FAIL: ' + str(r.error)}")
    if not r.ok:
        failures += 1
    return failures
