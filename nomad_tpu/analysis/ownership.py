"""nomadown runtime prong: snapshot-integrity fingerprints for owned structs.

The static rules (rules_ownership.py) reason about names; this module
watches the real objects. The control plane's correctness rests on a
copy-on-write convention (state/store.py module docstring): every struct
handed to the state store or proposed into the raft log becomes shared,
immutable MVCC history, readable by any snapshot forever after. Nothing
enforces that at runtime — an aliased mutation silently rewrites
history for every live snapshot and, through the FSM, can diverge
replicas (the PR-3 bug class).

Enabled via ``NOMAD_TPU_SAN=1`` (tests/conftest.py calls :func:`install`
alongside the nomadsan lock sanitizer), this module:

- registers every ``nomad_tpu.structs`` dataclass the moment it enters a
  ``VersionedTable`` (mvcc.py ``put``) or a commit event batch,
  recording a *fingerprint* — a stable hash over the dataclass fields,
  recursing through containers, nested dataclasses and numpy arrays;
- patches ``__setattr__`` on every struct dataclass (a tracking proxy)
  so an attribute write to a registered object is reported *at the
  mutating site*, with one sanctioned exception: writes made while the
  owning thread is inside the store's ``_begin``/``_commit`` window are
  the store stamping its own rows (create_index/modify_index/...) and
  only mark the entry for re-fingerprinting at commit;
- re-verifies fingerprints on every snapshot read (mvcc ``get`` /
  ``iterate``) and on event publish, throttled to once per object per
  commit epoch, which catches *interior* container mutation
  (``ev.queued_allocations[k] = v``) that no ``__setattr__`` proxy can
  see;
- exposes :func:`verify_all` for the chaos ``InvariantChecker`` and the
  modelcheck ``store_ownership`` scenario, so a schedule that mutates
  post-insert fails deterministically with a replayable seed.

Known limits (documented, deliberate):

- interior mutations are only caught at the next read/publish/sweep
  after the next commit (the per-epoch throttle keeps snapshot walks
  from re-hashing every row), and their mutating site is unknown —
  attribute-level writes are the precise ones;
- the registry holds strong references (slots dataclasses are not
  weakref-able) bounded to the most recent ``_MAX_TRACKED`` rows, so a
  mutation of a long-evicted row can be missed;
- fingerprints hash ``repr``-sorted sets and insertion-ordered dicts;
  they are compared only within one process, never persisted.

Violations never raise at the access site; they accumulate in
``OwnershipSanitizer.violations`` and the pytest plugin fails the run at
session end (exit code 3), same contract as nomadsan.
"""

from __future__ import annotations

import _thread
import dataclasses
import sys
import threading
import traceback
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

_REAL_LOCK = _thread.allocate_lock

# Frames inside these files are never a useful "who did it" answer.
_SKIP_FILES = (__file__, "mvcc.py", "threading.py")

# Top-level row types: cross-references between rows (Allocation.job,
# Evaluation payloads inside plans, ...) canonicalize as a shallow ref —
# the referenced row is fingerprinted under its own registry entry, and
# recursing would make one row's hash depend on another row's sanctioned
# in-txn restamping.
_ROW_TYPES = frozenset({
    "Job", "Node", "Allocation", "AllocBlock", "Evaluation",
    "Deployment", "Volume", "ServiceRegistration",
})

_MAX_TRACKED = 8192        # strong-ref registry bound (newest rows win)
_MAX_DEPTH = 8             # canonicalization recursion cap
_STRUCTS_PREFIX = "nomad_tpu.structs"


def _call_site(extra_skip: int = 0) -> str:
    """file:line of the nearest frame outside ownership/mvcc/threading."""
    f = sys._getframe(2 + extra_skip)
    while f is not None:
        fn = f.f_code.co_filename
        if not fn.endswith(_SKIP_FILES):
            return f"{fn}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


# -- fingerprinting ------------------------------------------------------


def _canon(obj: Any, depth: int, seen: set) -> Any:
    """Hashable canonical form of a struct value. Deterministic within a
    process; mutation of any reachable field/element changes it."""
    if obj is None or obj is True or obj is False:
        return obj
    t = type(obj)
    if t is int or t is float or t is str or t is bytes:
        return obj
    if depth >= _MAX_DEPTH:
        return ("<deep>", t.__qualname__)
    oid = id(obj)
    if oid in seen:
        return "<cycle>"
    seen.add(oid)
    try:
        if dataclasses.is_dataclass(obj):
            if depth > 0 and t.__qualname__ in _ROW_TYPES:
                return ("ref", t.__qualname__, getattr(obj, "id", ""))
            exempt = getattr(t, "_nomadown_exempt", ())
            # leading-underscore fields are derived caches by repo
            # convention (Node._avail_vec), not replicated state
            return (t.__qualname__,) + tuple(
                _canon(getattr(obj, f.name), depth + 1, seen)
                for f in dataclasses.fields(obj)
                if not f.name.startswith("_") and f.name not in exempt)
        if t is list or t is tuple:
            return ("L",) + tuple(_canon(x, depth + 1, seen) for x in obj)
        if t is dict:
            return ("D",) + tuple(
                (_canon(k, depth + 1, seen), _canon(v, depth + 1, seen))
                for k, v in obj.items())
        if t is set or t is frozenset:
            return ("S",) + tuple(
                sorted(repr(_canon(x, depth + 1, seen)) for x in obj))
        if isinstance(obj, np.ndarray):
            return ("A", obj.shape, str(obj.dtype), obj.tobytes())
        if isinstance(obj, np.generic):
            return obj.item()
        d = getattr(obj, "__dict__", None)
        if d is not None:
            return ("O", t.__qualname__) + tuple(
                (k, _canon(v, depth + 1, seen)) for k, v in sorted(d.items()))
        slots = getattr(t, "__slots__", None)
        if slots is not None:
            return ("O", t.__qualname__) + tuple(
                (s, _canon(getattr(obj, s, None), depth + 1, seen))
                for s in slots)
        return ("X", t.__qualname__)
    finally:
        seen.discard(oid)


def fingerprint(obj: Any) -> int:
    """Stable (per-process) hash over a struct's fields, recursive."""
    return hash(_canon(obj, 0, set()))


def _is_struct(obj: Any) -> bool:
    t = type(obj)
    return (dataclasses.is_dataclass(obj)
            and t.__module__.startswith(_STRUCTS_PREFIX))


def _each_struct(payload: Any) -> Iterator[Any]:
    """Structs inside an event payload: the payload itself, or one level
    of list/tuple (batched eval/alloc events)."""
    if _is_struct(payload):
        yield payload
    elif type(payload) in (list, tuple):
        for item in payload:
            if _is_struct(item):
                yield item


@dataclass
class Violation:
    kind: str            # "post-insert-mutation" | "snapshot-divergence"
    message: str
    stack: List[str] = field(default_factory=list)

    def render(self) -> str:
        return f"[{self.kind}] {self.message}"


class OwnershipSanitizer:
    """One fingerprint registry + tracking proxy. The module-level GLOBAL
    instance is what install()/the store hooks feed; tests snapshot and
    truncate its violation list around intentional triggers."""

    def __init__(self):
        self.active = False
        # raw lock: an instrumented one would recurse through nomadsan
        self._ilock = _REAL_LOCK()
        self._tls = threading.local()
        # id(obj) -> {"obj", "fp", "gen", "site", "epoch"}; strong refs,
        # LRU-bounded (slots dataclasses cannot be weakly referenced)
        self._tracked: "OrderedDict[int, Dict[str, Any]]" = OrderedDict()
        self.violations: List[Violation] = []
        self._epoch = 0
        self._patched = False

    # -- install / teardown -------------------------------------------

    def install(self) -> None:
        """Arm the registry and patch struct ``__setattr__`` (once; the
        wrappers are inert while ``active`` is False)."""
        self._patch_struct_classes()
        self.active = True

    def uninstall(self) -> None:
        self.active = False

    def forget_all(self) -> None:
        """Drop every tracked entry (test isolation helper)."""
        with self._ilock:
            self._tracked.clear()

    def _patch_struct_classes(self) -> None:
        if self._patched:
            return
        self._patched = True
        import importlib
        import pkgutil

        import nomad_tpu.structs as structs_pkg

        for info in pkgutil.iter_modules(structs_pkg.__path__):
            mod = importlib.import_module(f"{_STRUCTS_PREFIX}.{info.name}")
            for cls in vars(mod).values():
                if not (isinstance(cls, type) and dataclasses.is_dataclass(cls)):
                    continue
                if cls.__module__ != mod.__name__:
                    continue        # re-export; patched where defined
                if cls.__dataclass_params__.frozen:
                    continue        # frozen structs cannot be mutated
                if getattr(cls, "_nomadown_wrapped", False):
                    continue        # self or a base already routes here
                self._wrap_class(cls)

    def _wrap_class(self, cls: type) -> None:
        orig = cls.__setattr__
        san = self

        def __setattr__(obj, name, value):
            if san.active:
                san._on_setattr(obj, name, value)
            orig(obj, name, value)

        cls.__setattr__ = __setattr__
        cls._nomadown_wrapped = True

    # -- store txn window ----------------------------------------------

    def _depth(self) -> int:
        return getattr(self._tls, "depth", 0)

    def txn_begin(self) -> None:
        """StateStore._begin: writes by this thread until txn_commit are
        the store stamping its own rows, not aliasing bugs."""
        self._tls.depth = self._depth() + 1

    def txn_commit(self, gen: int, events: list) -> None:
        """StateStore._commit: re-fingerprint rows the store restamped,
        register event payload structs, close the window, bump the
        verify epoch."""
        dirty = getattr(self._tls, "dirty", None)
        if dirty:
            with self._ilock:
                for oid in dirty:
                    entry = self._tracked.get(oid)
                    if entry is not None:
                        try:
                            entry["fp"] = fingerprint(entry["obj"])
                        except Exception:
                            self._tracked.pop(oid, None)
                            continue
                        entry["gen"] = gen
            dirty.clear()
        for _kind, payload in events:
            for obj in _each_struct(payload):
                self.register(obj, gen)
        self._tls.depth = max(self._depth() - 1, 0)
        self._epoch += 1

    # -- registry ------------------------------------------------------

    def register(self, obj: Any, gen: int) -> None:
        """Fingerprint and track a struct that just became shared
        history. Called from mvcc put (table rows) and txn_commit (event
        payloads); no-op for non-struct values."""
        if not self.active or not _is_struct(obj):
            return
        try:
            fp = fingerprint(obj)
        except Exception:
            return
        oid = id(obj)
        site = _call_site()
        with self._ilock:
            self._tracked[oid] = {
                "obj": obj, "fp": fp, "gen": gen, "site": site,
                "epoch": self._epoch,
            }
            self._tracked.move_to_end(oid)
            while len(self._tracked) > _MAX_TRACKED:
                self._tracked.popitem(last=False)

    def tracked_count(self) -> int:
        with self._ilock:
            return len(self._tracked)

    def is_tracked(self, obj: Any) -> bool:
        entry = self._tracked.get(id(obj))
        return entry is not None and entry["obj"] is obj

    # -- tracking proxy callback ---------------------------------------

    def _on_setattr(self, obj: Any, name: str, value: Any) -> None:
        entry = self._tracked.get(id(obj))
        if entry is None or entry["obj"] is not obj:
            return
        if name.startswith("_") or name in getattr(obj, "_nomadown_exempt", ()):
            return      # derived caches, not replicated state
        try:
            old = getattr(obj, name)
        except AttributeError:
            old = entry     # sentinel: never equal to a field value
        if old is value or (type(old) is type(value)
                            and isinstance(old, (bool, int, float, str, bytes))
                            and old == value):
            return      # no-op rebind: the fingerprint cannot change
        if self._depth() > 0:
            dirty = getattr(self._tls, "dirty", None)
            if dirty is None:
                dirty = self._tls.dirty = set()
            dirty.add(id(obj))
            return
        self._report_mutation(entry, obj, name)

    def _report_mutation(self, entry: Dict[str, Any], obj: Any,
                         name: str) -> None:
        site = _call_site()
        ident = getattr(obj, "id", "") or ""
        with self._ilock:
            self._tracked.pop(id(obj), None)
        self.violations.append(Violation(
            "post-insert-mutation",
            f"{type(obj).__name__}{f'({ident})' if ident else ''}.{name} "
            f"written at {site} after the object entered the store at "
            f"{entry['site']} (gen {entry['gen']}) — committed rows are "
            "shared MVCC history; copy before mutating",
            stack=traceback.format_stack()[:-3]))

    # -- verification --------------------------------------------------

    def verify(self, obj: Any, gen: Optional[int] = None) -> None:
        """Snapshot-read / publish hook: recheck the fingerprint, at most
        once per object per commit epoch (interior-mutation detection)."""
        entry = self._tracked.get(id(obj))
        if entry is None or entry["obj"] is not obj:
            return
        if entry["epoch"] == self._epoch:
            return
        entry["epoch"] = self._epoch
        self._check_entry(entry, obj)

    def verify_all(self) -> int:
        """Full unthrottled sweep; returns the number of new violations.
        Used by the chaos InvariantChecker and modelcheck scenarios."""
        before = len(self.violations)
        with self._ilock:
            entries = list(self._tracked.values())
        for entry in entries:
            self._check_entry(entry, entry["obj"])
        return len(self.violations) - before

    def _check_entry(self, entry: Dict[str, Any], obj: Any) -> None:
        try:
            fp = fingerprint(obj)
        except Exception:
            return
        if fp == entry["fp"]:
            return
        ident = getattr(obj, "id", "") or ""
        with self._ilock:
            self._tracked.pop(id(obj), None)
        self.violations.append(Violation(
            "snapshot-divergence",
            f"{type(obj).__name__}{f'({ident})' if ident else ''} diverged "
            f"from its insert-time fingerprint (entered the store at "
            f"{entry['site']}, gen {entry['gen']}) — interior container "
            "mutation; attribute-level writes are reported at the "
            "mutating site",
            stack=traceback.format_stack()[:-3]))

    # -- reporting -----------------------------------------------------

    def check(self) -> None:
        if self.violations:
            raise AssertionError(
                "nomadown violations:\n"
                + "\n".join(v.render() for v in self.violations))

    def report(self) -> str:
        lines = [f"nomadown: {len(self.violations)} violation(s)"]
        for v in self.violations:
            lines.append("  " + v.render())
        return "\n".join(lines)


# -- module-level surface (what store/mvcc/events/conftest import) -------

GLOBAL = OwnershipSanitizer()


def install() -> None:
    GLOBAL.install()


def uninstall() -> None:
    GLOBAL.uninstall()


def enabled() -> bool:
    return GLOBAL.active


def violations() -> List[Violation]:
    return list(GLOBAL.violations)


def check() -> None:
    GLOBAL.check()


def verify_all() -> int:
    return GLOBAL.verify_all()
