"""nomadjit runtime prong: the launch ledger.

The static rules (rules_tensor.py) prove hazard *shapes* absent; this
module watches the real launches. Enabled via ``NOMAD_TPU_SAN=1`` (the
pytest plugin in tests/conftest.py calls :func:`install` before any
nomad_tpu module is imported), it

- registers a ``jax.monitoring`` duration listener for backend compiles
  (the event fires once per cold XLA compile and never on a warm cache
  hit — empirically the only public warm/cold signal), attributing each
  compile to the nearest non-jax stack frame and to the launch window
  open on that thread, if any;
- patches ``jax.device_put`` / ``jax.device_get`` with recording
  wrappers: these are the repo's SANCTIONED transfer sites (solver.py
  documents device_get as "the launch's ONLY host sync"), and every
  call lands in the ledger with call-site attribution;
- exposes :func:`window` — the per-launch ledger entry. Launch drivers
  (``solver._launch_guard``, ``placer._warm_launch``) open one window
  per launch, marked ``warm`` once the shape key has compiled. A
  compile inside a warm window and a second ``device_get`` inside any
  window are recorded as violations — the whole-suite generalization of
  the opt-in ``jit_guard.no_retrace`` discipline.

Known soundness limits (documented, deliberate):

- on CPU backends ``np.asarray(device_array)`` reads back through the
  buffer protocol, bypassing ``__array__`` and the transfer guard
  entirely (host and device share memory) — no runtime hook can see it.
  The static ``host-sync-in-launch`` rule covers those sites by name;
- implicit host->device transfers outside a guard window dispatch
  through C++ with no Python boundary to patch; inside warm windows
  ``jit_guard.no_retrace`` arms ``jax.transfer_guard("disallow")`` and
  reports each trip here via :func:`note_unsanctioned` before
  re-raising, so ``stats["unsanctioned_transfers"]`` is the count of
  transfers that escaped the sanctioned sites where detection is
  possible.

Violations never raise at the launch site (raising inside a monitoring
callback would corrupt the launch under test); they accumulate in
``LaunchLedger.violations`` and the pytest plugin fails the run at
session end (exit 3, same as nomadsan). The chaos
``InvariantChecker.check_launch_ledger`` sweep and the ``tensor_launch``
modelcheck scenario read the same instance. Tests can build private
:class:`LaunchLedger` instances so assertions don't pollute the global
run state.
"""

from __future__ import annotations

import _thread
import sys
import threading
import traceback
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Deque, Dict, Iterator, List, Optional

_REAL_LOCK = _thread.allocate_lock

# the monitoring event XLA fires once per backend compile (verified: no
# emission on warm cache hits, one per cold jit specialization)
COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
# bounded attribution: the ledger keeps the last N launch records and at
# most M attributed sites per record — enough to diagnose, never enough
# to leak memory on a long soak
MAX_RECORDS = 256
MAX_SITES = 16

_SKIP_FILES = (__file__, "threading.py", "contextlib.py")
_SKIP_DIRS = ("/jax/", "/jaxlib/", "/jax_plugins/")


def _call_site(extra_skip: int = 0) -> str:
    """file:line of the nearest frame outside the ledger and jax."""
    try:
        f = sys._getframe(2 + extra_skip)
    except ValueError:
        return "<unknown>"
    while f is not None:
        fn = f.f_code.co_filename
        if not fn.endswith(_SKIP_FILES) and not any(
                d in fn for d in _SKIP_DIRS):
            return f"{fn}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


@dataclass
class Violation:
    kind: str            # "warm-compile" | "extra-host-sync" | "unsanctioned-transfer"
    message: str
    stack: List[str] = field(default_factory=list)

    def render(self) -> str:
        return f"[{self.kind}] {self.message}"


@dataclass
class LaunchRecord:
    """One launch window's ledger entry."""
    name: str                     # launched kernel, e.g. "preempt_solve"
    key: object = None            # shape key the driver warms on
    warm: bool = False
    compiles: int = 0
    puts: int = 0
    gets: int = 0
    sites: List[str] = field(default_factory=list)
    open: bool = True

    def note(self, site: str) -> None:
        if len(self.sites) < MAX_SITES:
            self.sites.append(site)


class LaunchLedger:
    """One compile/transfer ledger. The module-level GLOBAL instance is
    what install()/the launch drivers feed; tests build private ones."""

    def __init__(self):
        self.active = False
        # raw lock: monitoring callbacks can fire under an instrumented
        # sanitizer lock and must not feed back into its order graph
        self._ilock = _REAL_LOCK()
        self._tls = threading.local()
        self.records: Deque[LaunchRecord] = deque(maxlen=MAX_RECORDS)
        self.violations: List[Violation] = []
        self.stats: Dict[str, int] = {
            "compiles": 0, "device_puts": 0, "device_gets": 0,
            "windows": 0, "warm_windows": 0, "unsanctioned_transfers": 0}
        self._listener_registered = False
        self._orig_put = None
        self._orig_get = None

    # -- global patching ----------------------------------------------

    def install(self) -> None:
        """Arm the compile listener and wrap the sanctioned transfer
        sites. Listener registration is once-per-process (jax exposes no
        deregistration that spares other listeners) and gated on
        ``active``, so uninstall() is still a clean revert."""
        if self.active:
            return
        import jax

        self.active = True
        if not self._listener_registered:
            jax.monitoring.register_event_duration_secs_listener(
                self._on_event_duration)
            self._listener_registered = True
        self._orig_put = jax.device_put
        self._orig_get = jax.device_get
        orig_put, orig_get = self._orig_put, self._orig_get
        ledger = self

        def device_put(*args, **kwargs):
            ledger._record_transfer("device_puts", _call_site())
            return orig_put(*args, **kwargs)

        def device_get(*args, **kwargs):
            ledger._record_transfer("device_gets", _call_site())
            return orig_get(*args, **kwargs)

        device_put.__name__ = "device_put"
        device_put.__doc__ = orig_put.__doc__
        device_get.__name__ = "device_get"
        device_get.__doc__ = orig_get.__doc__
        jax.device_put = device_put
        jax.device_get = device_get

    def uninstall(self) -> None:
        if not self.active:
            return
        import jax

        self.active = False
        if self._orig_put is not None:
            jax.device_put = self._orig_put
            jax.device_get = self._orig_get

    # -- signal intake -------------------------------------------------

    def _on_event_duration(self, event: str, duration: float,
                           **kwargs) -> None:
        if not self.active or event != COMPILE_EVENT:
            return
        site = _call_site()
        win = self._current()
        with self._ilock:
            self.stats["compiles"] += 1
        if win is None:
            return
        win.compiles += 1
        win.note(f"compile@{site}")
        if win.warm:
            with self._ilock:
                self.violations.append(Violation(
                    "warm-compile",
                    f"XLA compile inside warm launch window "
                    f"'{win.name}' (key={win.key!r}) at {site} — the "
                    "shape was promised compiled; an argument's "
                    "shape/dtype/weak-type drifted on the hot path",
                    stack=traceback.format_stack()[:-2]))

    def _record_transfer(self, kind: str, site: str) -> None:
        if not self.active:
            return
        with self._ilock:
            self.stats[kind] += 1
        win = self._current()
        if win is None:
            return
        if kind == "device_puts":
            win.puts += 1
            win.note(f"put@{site}")
            return
        win.gets += 1
        win.note(f"get@{site}")
        if win.gets == 2:
            with self._ilock:
                self.violations.append(Violation(
                    "extra-host-sync",
                    f"second jax.device_get inside launch window "
                    f"'{win.name}' at {site} — a launch gets ONE host "
                    "sync (solver.py launch contract)",
                    stack=traceback.format_stack()[:-2]))

    def note_unsanctioned(self, where: str) -> None:
        """A transfer guard tripped on an implicit transfer inside a
        guarded window (jit_guard reports it here before re-raising)."""
        if not self.active:
            return
        with self._ilock:
            self.stats["unsanctioned_transfers"] += 1
            self.violations.append(Violation(
                "unsanctioned-transfer",
                f"implicit host<->device transfer inside {where} — bytes "
                "moved outside the sanctioned device_put/device_get "
                "sites",
                stack=traceback.format_stack()[:-2]))

    # -- per-launch windows -------------------------------------------

    def _stack(self) -> List[LaunchRecord]:
        stack = getattr(self._tls, "windows", None)
        if stack is None:
            stack = self._tls.windows = []
        return stack

    def _current(self) -> Optional[LaunchRecord]:
        stack = self._stack()
        return stack[-1] if stack else None

    @contextmanager
    def window(self, name: str, key: object = None,
               warm: bool = False) -> Iterator[Optional[LaunchRecord]]:
        """Open one per-launch ledger entry on this thread. Compiles and
        sanctioned transfers that occur inside attribute to it; a warm
        window recording a compile is a violation."""
        if not self.active:
            yield None
            return
        rec = LaunchRecord(name=name, key=key, warm=warm)
        with self._ilock:
            self.stats["windows"] += 1
            if warm:
                self.stats["warm_windows"] += 1
            self.records.append(rec)
        self._stack().append(rec)
        try:
            yield rec
        finally:
            self._stack().pop()
            rec.open = False

    # -- reporting -----------------------------------------------------

    def verify_all(self, strict: bool = False) -> List[str]:
        """Rendered violations — the chaos invariant sweep's view of the
        ledger. With ``strict`` (callers that KNOW every launch thread
        has quiesced, e.g. the modelcheck scenario after joining), a
        window still open is a leak and reported too; the default sweep
        runs concurrently with live workers, where an open window on
        another thread is just a launch in flight."""
        out = [v.render() for v in self.violations]
        if strict:
            with self._ilock:
                leaked = [r for r in self.records if r.open]
            for r in leaked:
                out.append(f"[leaked-window] launch window '{r.name}' "
                           f"(key={r.key!r}) never closed")
        return out

    def check(self) -> None:
        if self.violations:
            raise AssertionError(
                "nomadjit violations:\n"
                + "\n".join(v.render() for v in self.violations))

    def report(self) -> str:
        s = self.stats
        lines = [
            f"nomadjit: {len(self.violations)} violation(s); "
            f"compiles={s['compiles']} device_puts={s['device_puts']} "
            f"device_gets={s['device_gets']} windows={s['windows']} "
            f"(warm={s['warm_windows']}) "
            f"unsanctioned_transfers={s['unsanctioned_transfers']}"]
        for v in self.violations:
            lines.append("  " + v.render())
        return "\n".join(lines)


# -- module-level surface (what launch drivers + conftest import) --------

GLOBAL = LaunchLedger()


def install() -> None:
    GLOBAL.install()


def uninstall() -> None:
    GLOBAL.uninstall()


def enabled() -> bool:
    return GLOBAL.active


@contextmanager
def window(name: str, key: object = None,
           warm: bool = False) -> Iterator[Optional[LaunchRecord]]:
    """Per-launch ledger window on the GLOBAL ledger (no-op while the
    sanitizer switch is off — launch drivers call this unconditionally)."""
    with GLOBAL.window(name, key=key, warm=warm) as rec:
        yield rec


def note_unsanctioned(where: str) -> None:
    GLOBAL.note_unsanctioned(where)


def violations() -> List[Violation]:
    return list(GLOBAL.violations)


def check() -> None:
    GLOBAL.check()
