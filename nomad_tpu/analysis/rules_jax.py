"""Rule `jax-hot-path`: no host syncs or trace hazards inside jit.

The scheduling kernels' whole value is staying on-device: one host sync
(`.item()`, `np.asarray`, `block_until_ready`, `jax.device_get`,
`float()` on a tracer) inside a `@jax.jit` body either fails at trace
time or — worse — silently forces a device round-trip per call and
erases the BENCH win. Python `if`/`while` on a traced argument is the
recompilation/ConcretizationError trap: each new value re-traces.

Allowed and not flagged: branching on `static_argnames` parameters, on
`x is None` (structure, static under jit), and on shape/dtype metadata
(`x.shape`, `x.ndim`, `x.size`, `x.dtype`, `len(x)`) — all static at
trace time.

Scope: tensor/ and scheduler/ inside the package; everywhere in
standalone fixture trees.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import AnalysisContext, Finding, Module, in_scope, rule

SCOPE = ("tensor", "scheduler")
SYNC_METHODS = {"item", "tolist", "block_until_ready"}
SYNC_NUMPY_ALIASES = {"np", "numpy", "onp"}
STATIC_ATTRS = {"shape", "ndim", "size", "dtype"}


def _jit_decoration(dec: ast.expr) -> Optional[Tuple[str, ...]]:
    """Return static_argnames if `dec` is a jit decorator, else None."""
    # @jax.jit / @jit
    if isinstance(dec, ast.Attribute) and dec.attr == "jit":
        return ()
    if isinstance(dec, ast.Name) and dec.id == "jit":
        return ()
    if not isinstance(dec, ast.Call):
        return None
    func = dec.func
    # @jax.jit(...) / @jit(...)
    if ((isinstance(func, ast.Attribute) and func.attr == "jit")
            or (isinstance(func, ast.Name) and func.id == "jit")):
        return _static_argnames(dec)
    # @partial(jax.jit, ...) / @functools.partial(jit, ...)
    is_partial = ((isinstance(func, ast.Name) and func.id == "partial")
                  or (isinstance(func, ast.Attribute)
                      and func.attr == "partial"))
    if is_partial and dec.args:
        inner = _jit_decoration(dec.args[0])
        if inner is not None:
            return _static_argnames(dec)
    return None


def _static_argnames(call: ast.Call) -> Tuple[str, ...]:
    for kw in call.keywords:
        if kw.arg != "static_argnames":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            return tuple(e.value for e in v.elts
                         if isinstance(e, ast.Constant))
    return ()


def _jitted_functions(mod: Module) -> Dict[ast.FunctionDef, Tuple[str, ...]]:
    """All jit-compiled defs in the module with their static argnames:
    decorated defs, plus defs wrapped by module-level assignments like
    `solve = partial(jax.jit, ...)(_impl)` or `solve = jax.jit(_impl)`."""
    out: Dict[ast.FunctionDef, Tuple[str, ...]] = {}
    by_name: Dict[str, ast.FunctionDef] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.FunctionDef):
            by_name.setdefault(node.name, node)
            for dec in node.decorator_list:
                statics = _jit_decoration(dec)
                if statics is not None:
                    out[node] = statics
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Assign) or not isinstance(
                node.value, ast.Call):
            continue
        call = node.value
        # jax.jit(fn, ...) form
        statics = None
        target_fn = None
        func = call.func
        if ((isinstance(func, ast.Attribute) and func.attr == "jit")
                or (isinstance(func, ast.Name) and func.id == "jit")):
            statics = _static_argnames(call)
            if call.args and isinstance(call.args[0], ast.Name):
                target_fn = by_name.get(call.args[0].id)
        # partial(jax.jit, ...)(fn) form
        elif isinstance(func, ast.Call):
            statics = _jit_decoration(func)
            if statics is not None and call.args and isinstance(
                    call.args[0], ast.Name):
                target_fn = by_name.get(call.args[0].id)
        if target_fn is not None and statics is not None:
            out.setdefault(target_fn, statics)
    return out


def _param_names(fn: ast.FunctionDef) -> Set[str]:
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    return set(names)


def _is_none_check(parents: Dict[ast.AST, ast.AST], name: ast.Name) -> bool:
    p = parents.get(name)
    return (isinstance(p, ast.Compare)
            and all(isinstance(op, (ast.Is, ast.IsNot)) for op in p.ops)
            and all(isinstance(c, ast.Constant) and c.value is None
                    for c in p.comparators))


def _traced_uses(test: ast.expr, traced: Set[str]) -> List[ast.Name]:
    """Names in `test` that read a traced value non-statically."""
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(test):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    bad = []
    for node in ast.walk(test):
        if not isinstance(node, ast.Name) or node.id not in traced:
            continue
        p = parents.get(node)
        if isinstance(p, ast.Attribute) and p.attr in STATIC_ATTRS:
            continue
        if (isinstance(p, ast.Call) and isinstance(p.func, ast.Name)
                and p.func.id in ("len", "isinstance")):
            continue
        if _is_none_check(parents, node):
            continue
        bad.append(node)
    return bad


def _check_jitted(mod: Module, fn: ast.FunctionDef,
                  statics: Tuple[str, ...]) -> List[Finding]:
    findings: List[Finding] = []
    traced = _param_names(fn) - set(statics)
    qual = f"{mod.rel}:{fn.name}"

    def add(node, message, detail):
        findings.append(Finding(
            rule="jax-hot-path", path=mod.rel, line=node.lineno,
            severity="error", message=message, context=qual, detail=detail))

    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute):
                if func.attr in SYNC_METHODS:
                    add(node, f"host sync .{func.attr}() inside @jax.jit "
                        f"'{fn.name}' forces a device round-trip per call",
                        f".{func.attr}")
                elif (isinstance(func.value, ast.Name)
                      and func.value.id in SYNC_NUMPY_ALIASES):
                    add(node, f"numpy call {func.value.id}.{func.attr}() "
                        f"inside @jax.jit '{fn.name}' concretizes the "
                        "tracer (host sync or trace error)",
                        f"{func.value.id}.{func.attr}")
                elif (func.attr == "device_get"
                      and isinstance(func.value, ast.Name)
                      and func.value.id == "jax"):
                    add(node, f"jax.device_get inside @jax.jit '{fn.name}' "
                        "is a host sync", "jax.device_get")
            elif (isinstance(func, ast.Name)
                  and func.id in ("float", "int", "bool")
                  and len(node.args) == 1):
                arg = node.args[0]
                ok = (isinstance(arg, ast.Constant)
                      or (isinstance(arg, ast.Name) and arg.id in statics)
                      or (isinstance(arg, ast.Call)
                          and isinstance(arg.func, ast.Name)
                          and arg.func.id == "len")
                      or (isinstance(arg, ast.Attribute)
                          and arg.attr in STATIC_ATTRS)
                      or (isinstance(arg, ast.Subscript)
                          and isinstance(arg.value, ast.Attribute)
                          and arg.value.attr in STATIC_ATTRS))
                if not ok:
                    add(node, f"{func.id}() on a (possibly traced) value "
                        f"inside @jax.jit '{fn.name}' concretizes the "
                        "tracer; use jnp ops instead", f"{func.id}()")
        elif isinstance(node, (ast.If, ast.While)):
            for use in _traced_uses(node.test, traced):
                kind = "while" if isinstance(node, ast.While) else "if"
                add(node, f"Python `{kind}` branches on traced argument "
                    f"'{use.id}' inside @jax.jit '{fn.name}' — re-traces "
                    "per value (use jnp.where / lax.cond / mark it "
                    "static_argnames)", f"{kind}:{use.id}")
    return findings


@rule("jax-hot-path",
      "no host syncs or traced-value Python branching inside "
      "jit-compiled scheduling kernels")
def check_jax_hot_path(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for mod in ctx.modules:
        if not in_scope(mod.rel, SCOPE):
            continue
        for fn, statics in _jitted_functions(mod).items():
            findings.extend(_check_jitted(mod, fn, statics))
    return findings
