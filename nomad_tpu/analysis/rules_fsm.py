"""Rule `fsm-determinism`: no nondeterminism reachable from raft apply.

Every replica applies the identical raft log; any function reachable
from the FSM apply dispatch (the methods named in raft/fsm.py's
MUTATIONS set, plus FSM.apply itself) must therefore compute identical
results from identical arguments. Wall-clock reads, RNGs, uuid minting,
and set-iteration orders (string hashing is per-process randomized) all
break that and fork replica state silently — the bug only surfaces much
later as divergent GC/scheduling decisions.

Timestamps must instead ride the replicated command from the proposer
(raft/fsm.py TIMESTAMPED + StateStore._clock), which is exactly what
this rule keeps honest.

Python dict iteration is insertion-ordered and therefore deterministic
given a deterministic insert sequence, so plain dict/.keys()/.items()
iteration is NOT flagged; set/frozenset iteration is, unless wrapped in
sorted().
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .callgraph import CallGraph, FuncInfo
from .core import AnalysisContext, Finding, Module, in_scope, rule

# The determinism contract binds the FSM dispatch and the state store it
# mutates; the call graph is built over exactly those layers. A wider
# graph drowns in name-collision edges (every `.wait()`/`.add()` in the
# package), and the layers outside it run on ONE node pre-proposal where
# wall-clock/random are legitimate.
FSM_SCOPE = ("raft", "state")

ROOT_SET_NAMES = ("MUTATIONS",)
ROOT_CLASS_METHODS = (("FSM", "apply"),)

# modules whose attribute calls are nondeterministic across replicas
NONDET_CALLS = {
    ("time", "time"), ("time", "time_ns"), ("time", "monotonic"),
    ("time", "monotonic_ns"), ("time", "perf_counter"),
    ("time", "perf_counter_ns"),
    ("uuid", "uuid1"), ("uuid", "uuid4"),
    ("os", "urandom"),
    ("datetime", "now"), ("datetime", "utcnow"), ("date", "today"),
}
NONDET_MODULE_PREFIXES = ("random", "secrets")


def _dotted(node: ast.expr) -> Optional[List[str]]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None


def _nondet_call(call: ast.Call) -> Optional[str]:
    parts = _dotted(call.func)
    if not parts or len(parts) < 2:
        return None
    dotted = ".".join(parts)
    if parts[0] in NONDET_MODULE_PREFIXES:
        return dotted
    # np.random.*, numpy.random.* (jax.random is key-driven: deterministic)
    if parts[0] in ("np", "numpy") and "random" in parts[1:]:
        return dotted
    if tuple(parts[-2:]) in NONDET_CALLS:
        return dotted
    return None


def _collect_roots(modules: List[Module], cg: CallGraph) -> List[FuncInfo]:
    names: Set[str] = set()
    for mod in modules:
        for stmt in mod.tree.body:
            if not isinstance(stmt, ast.Assign):
                continue
            for target in stmt.targets:
                if (isinstance(target, ast.Name)
                        and target.id in ROOT_SET_NAMES
                        and isinstance(stmt.value, ast.Set)):
                    for elt in stmt.value.elts:
                        if isinstance(elt, ast.Constant) and isinstance(
                                elt.value, str):
                            names.add(elt.value)
    roots = [f for f in cg.functions if f.name in names]
    for cls, meth in ROOT_CLASS_METHODS:
        roots.extend(f for f in cg.functions
                     if f.class_name == cls and f.name == meth)
    return roots


class _SetIterVisitor(ast.NodeVisitor):
    """Per-function scan for iteration over set-typed expressions.

    Tracks simple local bindings (`x = set(...)` / `x = {a, b}` /
    `x = {... for ...}`) so `for k in jobs_touched:` is caught, and
    clears the binding on any other reassignment."""

    def __init__(self):
        self.set_locals: Set[str] = set()
        self.hits: List[ast.AST] = []

    @staticmethod
    def _is_set_expr(node: ast.expr, set_locals: Set[str]) -> bool:
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id in ("set", "frozenset")):
            return True
        if isinstance(node, ast.Name) and node.id in set_locals:
            return True
        if isinstance(node, ast.BinOp) and isinstance(
                node.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)):
            # set algebra: a | b, a - b, ...
            return (_SetIterVisitor._is_set_expr(node.left, set_locals)
                    or _SetIterVisitor._is_set_expr(node.right, set_locals))
        return False

    def visit_Assign(self, node: ast.Assign):
        for target in node.targets:
            if isinstance(target, ast.Name):
                if self._is_set_expr(node.value, self.set_locals):
                    self.set_locals.add(target.id)
                else:
                    self.set_locals.discard(target.id)
        self.generic_visit(node)

    def _check_iter(self, node: ast.AST, iter_expr: ast.expr):
        if self._is_set_expr(iter_expr, self.set_locals):
            self.hits.append(node)

    def visit_For(self, node: ast.For):
        self._check_iter(node, node.iter)
        self.generic_visit(node)

    def _visit_comp(self, node):
        for gen in node.generators:
            self._check_iter(node, gen.iter)
        self.generic_visit(node)

    visit_ListComp = visit_SetComp = visit_GeneratorExp = _visit_comp

    def visit_DictComp(self, node: ast.DictComp):
        self._visit_comp(node)


@rule("fsm-determinism",
      "no wall-clock/RNG/uuid/set-order nondeterminism reachable from "
      "raft FSM apply")
def check_fsm_determinism(ctx: AnalysisContext) -> List[Finding]:
    modules = [m for m in ctx.modules if in_scope(m.rel, FSM_SCOPE)]
    cg = CallGraph(modules)
    roots = _collect_roots(modules, cg)
    if not roots:
        return []
    reachable = cg.reachable(roots)
    by_rel: Dict[str, Module] = {m.rel: m for m in modules}
    findings: List[Finding] = []
    for fn in sorted(reachable, key=lambda f: (f.module_rel, f.qualname)):
        mod = by_rel[fn.module_rel]
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Call):
                dotted = _nondet_call(node)
                if dotted is not None:
                    findings.append(Finding(
                        rule="fsm-determinism", path=fn.module_rel,
                        line=node.lineno, severity="error",
                        message=(f"nondeterministic call {dotted}() in a "
                                 "function reachable from FSM apply — "
                                 "replicas applying the same log entry "
                                 "would diverge; thread the value through "
                                 "the replicated command instead"),
                        context=f"{fn.module_rel}:{fn.qualname}",
                        detail=dotted))
        visitor = _SetIterVisitor()
        visitor.visit(fn.node)
        for node in visitor.hits:
            findings.append(Finding(
                rule="fsm-determinism", path=fn.module_rel,
                line=node.lineno, severity="error",
                message=("iteration over a set in a function reachable "
                         "from FSM apply — set order is hash-randomized "
                         "per process; iterate sorted(...) instead"),
                context=f"{fn.module_rel}:{fn.qualname}",
                detail=f"set-iteration@{node.lineno - fn.node.lineno}"))
    return findings
