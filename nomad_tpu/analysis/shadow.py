"""nomadflow runtime prong: the shadow-state differential sanitizer.

The static rules (rules_flow.py) prove the mutation→event contract's
*shape*; this module proves its *values*. Enabled via ``NOMAD_TPU_SAN=1``
(tests/conftest.py installs it with the other runtime prongs), it
attaches one :class:`ShadowReplica` per (store, broker) pair — the
server wires this automatically at broker construction — which

- subscribes to the broker's Allocation/Node/Evaluation topics and
  replays every delta into reduced replicas: alloc rows keyed by id
  (modify index, statuses, node, resource vector), node and eval rows
  keyed by id, columnar ``AllocBlock`` payloads expanded through the
  same ``iter_allocs`` materialization the MVCC snapshot uses, promoted
  block positions overridden by their row events exactly as the store
  overrides them;
- treats ring truncation and the ``restore`` sentinel as a RESYNC, not
  a violation: the replica rebuilds from a fresh snapshot, which is the
  contract every delta consumer (AllocSyncHub, the device-resident
  incremental state in ``tensor/incremental.py``) must honor;
- every K commits — and on demand from the chaos invariant sweep
  (``check_event_completeness``, invariant 8) — fingerprint-compares the
  replicas against a fresh MVCC snapshot rebuild, per-node usage columns
  included, computed on BOTH sides by the same vectorized scatter
  (:func:`usage_columns`, the PR 10 columnar path) over identically
  sorted rows so float sums are bit-exact by construction. Any
  divergence — a missed delta, a reordered overwrite, a narrowed
  payload — is a violation.

The delta-folding semantics themselves (kind dispatch, block expansion,
promotion override, GC pops) live in ``state/deltas.py`` — one
implementation shared with the incremental device state, so the
sanitizer proves the exact replay rules the scheduler runs on.

The replay runs inline on the commit listener (serialized under the
store's write lock, after the broker's own listener has appended the
events), so the drained subscription is always exactly caught up with
the commit being compared — the gauge ``nomad.events.delta_lag`` (commit
index minus shadow-applied index) therefore reads 0 until consumption
moves off the commit path, which is precisely the number the
incremental-state feed watches grow.

Violations never raise at the commit site (that would poison the store's
write path mid-transaction); they accumulate on the tracker and the
pytest plugin fails the session exit-3, same as nomadsan/nomadown/
nomadjit. Tests build private :class:`ShadowTracker` instances.
"""

from __future__ import annotations

import _thread
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from ..state.deltas import (
    ALLOC_ROW_KINDS, CLIENT_TERMINAL, NODE_KINDS, REPLAY_TOPICS,
    EntryReplica, alloc_entry as _alloc_entry, client_terminal,
    eval_entry as _eval_entry, node_entry as _node_entry, usage_columns,
)

_REAL_LOCK = _thread.allocate_lock

# default commit cadence between fingerprint compares; the chaos sweep
# and scenario teardowns force extra compares on top
COMPARE_EVERY = 64
# bounded diff rendering: enough ids to diagnose, never enough to flood
MAX_DIFF_IDS = 8

SHADOW_TOPICS = REPLAY_TOPICS


def _client_terminal(status: str) -> bool:
    return client_terminal(status)


@dataclass
class Violation:
    kind: str            # "missed-delta" | "shadow-divergence"
    message: str
    stack: List[str] = field(default_factory=list)

    def render(self) -> str:
        return f"[{self.kind}] {self.message}"


def _diff_maps(label: str, shadow: dict, truth: dict) -> List[str]:
    out = []
    missing = sorted(set(truth) - set(shadow))
    extra = sorted(set(shadow) - set(truth))
    stale = sorted(k for k in shadow.keys() & truth.keys()
                   if shadow[k] != truth[k])
    if missing:
        out.append(f"{label}: {len(missing)} id(s) in the store but never "
                   f"delivered as deltas {missing[:MAX_DIFF_IDS]}")
    if extra:
        out.append(f"{label}: {len(extra)} id(s) delivered as deltas but "
                   f"absent from the store {extra[:MAX_DIFF_IDS]}")
    if stale:
        k = stale[0]
        out.append(f"{label}: {len(stale)} id(s) stale "
                   f"{stale[:MAX_DIFF_IDS]}; first: shadow={shadow[k]!r} "
                   f"store={truth[k]!r}")
    return out


class ShadowReplica(EntryReplica):
    """Event-derived reduction of one store, compared against MVCC
    snapshot rebuilds every `every` commits. The replay rules are
    :class:`state.deltas.EntryReplica`'s — shared verbatim with the
    incremental device state."""

    def __init__(self, store, broker, tracker: "ShadowTracker",
                 every: int = COMPARE_EVERY):
        EntryReplica.__init__(self)
        self.store = store
        self.tracker = tracker
        self.every = max(1, every)
        self.sub = broker.subscribe(dict(SHADOW_TOPICS))
        self.applied_index = 0
        self.commits = 0
        self.compares = 0
        self.resyncs = 0
        # raw lock: the listener runs under the store's (instrumented)
        # write lock; the shadow's own serialization must not feed the
        # sanitizer's lock-order graph
        self._lock = _REAL_LOCK()
        self._resync_locked()   # adopt whatever state predates the attach
        store.add_commit_listener(self._on_commit)

    @property
    def _promoted(self) -> Set[str]:
        return self.promoted

    # -- commit listener ----------------------------------------------

    def _on_commit(self, gen: int, events: list) -> None:
        if not self.tracker.active:
            return
        from ..core.metrics import REGISTRY
        with self._lock:
            evs = self.sub.next_events(timeout=0)
            if self.sub.truncated:
                # a lapped ring or the restore sentinel: the contract
                # answer is a full resync, never incremental patching
                self.sub.truncated = False
                self._resync_locked()
            else:
                for e in evs:
                    self._apply(e)
            self.applied_index = gen
            self.commits += 1
            REGISTRY.set_gauge("nomad.events.delta_lag",
                               float(self.store._index - self.applied_index))
            if self.commits % self.every == 0:
                self._compare_locked()

    # -- delta replay --------------------------------------------------

    def _apply(self, e) -> None:
        # kept as a named seam: tests monkeypatch this to drop kinds
        EntryReplica.apply(self, e)

    def _resync_locked(self) -> None:
        self.resync_from(self.store)
        self.resyncs += 1

    # -- differential compare -----------------------------------------

    def _compare_locked(self) -> Optional[str]:
        snap = self.store.snapshot()
        try:
            truth_allocs = {a.id: _alloc_entry(a) for a in snap.allocs()}
            truth_nodes = {n.id: _node_entry(n) for n in snap.nodes()}
            truth_evals = {e.id: _eval_entry(e) for e in snap.evals()}
            index = snap.index
        finally:
            snap.close()
        self.compares += 1
        diffs = (_diff_maps("allocs", self.allocs, truth_allocs)
                 + _diff_maps("nodes", self.nodes, truth_nodes)
                 + _diff_maps("evals", self.evals, truth_evals))
        if not diffs:
            # alloc sets match — now the columnar reduction must too,
            # through the same scatter the tensor state uses
            su, tu = usage_columns(self.allocs), usage_columns(truth_allocs)
            if su != tu:
                bad = sorted(k for k in su.keys() | tu.keys()
                             if su.get(k) != tu.get(k))
                diffs = [f"usage columns diverge on {len(bad)} node(s) "
                         f"{bad[:MAX_DIFF_IDS]}"]
        if not diffs:
            return None
        msg = (f"shadow replica diverged from snapshot rebuild at "
               f"index {index} (commit {self.commits}, "
               f"{self.resyncs} resync(s)): " + "; ".join(diffs))
        self.tracker.record(Violation("shadow-divergence", msg))
        return msg

    def force_compare(self) -> Optional[str]:
        """Drain + compare now (invariant sweeps, scenario teardowns)."""
        with self._lock:
            evs = self.sub.next_events(timeout=0)
            if self.sub.truncated:
                self.sub.truncated = False
                self._resync_locked()
            else:
                for e in evs:
                    self._apply(e)
            return self._compare_locked()


class ShadowTracker:
    """Registry of shadow replicas. The module-level GLOBAL instance is
    what conftest installs and the server attaches to; tests build
    private ones."""

    def __init__(self, every: int = COMPARE_EVERY):
        self.active = False
        self.every = every
        self._ilock = _REAL_LOCK()
        self.replicas: List[ShadowReplica] = []
        self.violations: List[Violation] = []

    def install(self) -> None:
        self.active = True

    def uninstall(self) -> None:
        self.active = False

    def attach(self, store, broker,
               every: Optional[int] = None) -> Optional[ShadowReplica]:
        """Attach a replica to a (store, broker) pair. No-op while the
        sanitizer switch is off — the server calls this unconditionally."""
        if not self.active:
            return None
        rep = ShadowReplica(store, broker, self,
                            every=every or self.every)
        with self._ilock:
            self.replicas.append(rep)
        return rep

    def record(self, v: Violation) -> None:
        with self._ilock:
            self.violations.append(v)

    def verify_all(self) -> List[str]:
        """Force-compare every replica; rendered violations after.
        The chaos invariant sweep's view of the shadow state."""
        with self._ilock:
            reps = list(self.replicas)
        for rep in reps:
            rep.force_compare()
        return [v.render() for v in self.violations]

    def check(self) -> None:
        if self.violations:
            raise AssertionError(
                "nomadflow violations:\n"
                + "\n".join(v.render() for v in self.violations))

    def stats(self) -> Dict[str, int]:
        with self._ilock:
            reps = list(self.replicas)
        return {
            "replicas": len(reps),
            "commits": sum(r.commits for r in reps),
            "compares": sum(r.compares for r in reps),
            "resyncs": sum(r.resyncs for r in reps),
        }

    def report(self) -> str:
        s = self.stats()
        lines = [
            f"nomadflow: {len(self.violations)} violation(s); "
            f"replicas={s['replicas']} commits={s['commits']} "
            f"compares={s['compares']} resyncs={s['resyncs']}"]
        for v in self.violations:
            lines.append("  " + v.render())
        return "\n".join(lines)


# -- module-level surface (what the server + conftest import) -------------

GLOBAL = ShadowTracker()


def install() -> None:
    GLOBAL.install()


def uninstall() -> None:
    GLOBAL.uninstall()


def enabled() -> bool:
    return GLOBAL.active


def maybe_attach(store, broker) -> Optional[ShadowReplica]:
    """Server-side hook: attach a GLOBAL replica when the sanitizer is
    armed, a no-op otherwise."""
    return GLOBAL.attach(store, broker)


def violations() -> List[Violation]:
    return list(GLOBAL.violations)


def check() -> None:
    GLOBAL.check()
