"""nomadown static prong: ownership/aliasing rules for owned structs.

The control plane's copy-on-write discipline (state/store.py module
docstring) says a struct handed to the state store or proposed into the
raft log becomes shared immutable history. These rules encode that
discipline as an interprocedural escape-and-mutation analysis over the
callgraph.py machinery:

- ``store-escape-mutation``: an object passed to a StateStore
  ``upsert_*``/``_put_*`` sink or a raft ``propose``/``apply`` sink is
  attribute-mutated afterwards in the same function — directly, or by
  being passed to a callee whose (transitively computed) summary says
  it mutates that parameter.
- ``read-mutate-no-copy``: the interprocedural complement of the
  intra-procedural ``shared-struct-mutation`` rule (rules_hygiene.py) —
  a local bound from a store getter/snapshot iterator is handed to a
  mutating callee, container-mutated (``ev.tags.append``), or
  key-assigned, without an intervening copy/rebind. Direct attribute
  assignments stay with the hygiene rule so a site is never flagged
  twice.
- ``propose-retain-alias``: a method proposes an object into the raft
  log AND retains it (``self.pending[id] = ev``); any method of the
  same class that pulls from that attribute and mutates the result is
  mutating replicated log history through the retained alias.
- ``publish-after-mutate``: a struct already appended to a commit-event
  batch (the list handed to ``_commit``/``publish``) is mutated before
  the batch is published — the event ring holds payloads by reference,
  so subscribers would see the post-mutation state attributed to the
  pre-mutation index.

Mutation summaries are a fixpoint: a function mutates parameter ``p``
if it attribute-mutates ``p`` (or an element alias of ``p`` bound by a
``for``/subscript), or passes ``p`` to any resolution candidate that
mutates the matching parameter. Resolution inherits callgraph.py's
deliberate over-approximation; findings are fixed in-code per repo
tradition (baseline.json stays empty) or suppressed with an inline
``# san-ok: <reason>``.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import CallGraph, FuncInfo
from .core import AnalysisContext, Finding, Module, in_scope, rule
from .rules_concurrency import MUTATORS, _analysis_scope, _suppressed
from .rules_hygiene import _read_call, _target_names

OWNERSHIP_RULES = ("store-escape-mutation", "read-mutate-no-copy",
                   "propose-retain-alias", "publish-after-mutate")

# Where owned structs actually flow. mock.py/testing.py build fixtures
# that land in stores, so they are part of the discipline.
OWNERSHIP_SCOPE = ("core", "raft", "state", "scheduler", "client", "chaos",
                   "obs", "api", "tensor", "mock.py", "testing.py")
PUBLISH_SCOPE = ("state", "core", "raft")
RETAIN_SCOPE = ("core", "raft", "scheduler", "state")

RAFT_VERBS = {"propose", "propose_async"}
APPLY_VERBS = {"apply", "apply_async"}
RAFTISH_TOKENS = ("raft", "fsm")
EVENT_SINK_NAMES = {"_commit", "publish", "_on_commit"}
_MAX_ARG_DEPTH = 4


def _store_sink_name(call: ast.Call) -> Optional[str]:
    func = call.func
    name = None
    if isinstance(func, ast.Attribute):
        name = func.attr
    elif isinstance(func, ast.Name):
        name = func.id
    if name and (name.startswith("upsert_") or name.startswith("_put_")):
        return name
    return None


def _raft_sink_name(call: ast.Call) -> Optional[str]:
    func = call.func
    if not isinstance(func, ast.Attribute):
        return None
    if func.attr in RAFT_VERBS:
        return func.attr
    if func.attr in APPLY_VERBS:
        # only apply/apply_async on a raft-ish receiver (fsm.apply,
        # self._raft.apply, node.raft.apply) — not e.g. pool.apply
        recv, tokens = func.value, []
        while isinstance(recv, ast.Attribute):
            tokens.append(recv.attr)
            recv = recv.value
        if isinstance(recv, ast.Name):
            tokens.append(recv.id)
        if any(tok in t.lower() for t in tokens for tok in RAFTISH_TOKENS):
            return func.attr
    return None


def _deep_names(node: ast.expr, depth: int = 0) -> Set[str]:
    """Names reachable through display-literal nesting — the raft
    command-tuple shape ``(op, ([ev],), {"ts": ts})`` included."""
    out: Set[str] = set()
    if depth > _MAX_ARG_DEPTH:
        return out
    if isinstance(node, ast.Name):
        out.add(node.id)
    elif isinstance(node, (ast.List, ast.Tuple, ast.Set)):
        for elt in node.elts:
            out |= _deep_names(elt, depth + 1)
    elif isinstance(node, ast.Dict):
        for v in node.values:
            if v is not None:
                out |= _deep_names(v, depth + 1)
    elif isinstance(node, ast.Starred):
        out |= _deep_names(node.value, depth + 1)
    return out


def _attr_chain(node: ast.expr) -> Tuple[Optional[str], List[str]]:
    """(root name, attribute chain) for ``name.a.b`` — (None, []) when
    the chain does not bottom out in a plain Name."""
    attrs: List[str] = []
    while isinstance(node, ast.Attribute):
        attrs.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        return node.id, list(reversed(attrs))
    return None, []


def _params(fn_node: ast.AST) -> List[str]:
    a = fn_node.args
    names = [x.arg for x in
             list(getattr(a, "posonlyargs", [])) + a.args + a.kwonlyargs]
    if names and names[0] in ("self", "cls"):
        names = names[1:]
    return names


@dataclass
class _CallRec:
    line: int
    kind: str                       # "self" | "name" | "attr"
    name: str
    pos: List[Tuple[int, str]] = field(default_factory=list)
    kws: List[Tuple[str, str]] = field(default_factory=list)
    elems: List[Tuple[int, str]] = field(default_factory=list)
    is_sink: bool = False


@dataclass
class _FnFacts:
    """Lineno-keyed facts about one function (closures included; loop
    back-edges are deliberately ignored — source order only)."""
    sinks_store: List[Tuple[int, str, Set[str]]] = field(default_factory=list)
    sinks_raft: List[Tuple[int, str, Set[str]]] = field(default_factory=list)
    event_appends: List[Tuple[int, Set[str]]] = field(default_factory=list)
    # (line, root, what, via) — via in {assign, augassign, del, mcall,
    # subscript}
    mutations: List[Tuple[int, str, str, str]] = field(default_factory=list)
    calls: List[_CallRec] = field(default_factory=list)
    rebinds: Dict[str, List[int]] = field(default_factory=dict)
    retains: List[Tuple[int, str, str]] = field(default_factory=list)
    self_reads: List[Tuple[int, str, str]] = field(default_factory=list)
    taints: List[Tuple[int, str]] = field(default_factory=list)
    list_members: Dict[str, Set[str]] = field(default_factory=dict)
    alias: Dict[str, str] = field(default_factory=dict)

    def root(self, name: str) -> str:
        seen = 0
        while name in self.alias and seen < 2:
            name = self.alias[name]
            seen += 1
        return name

    def rebound_between(self, name: str, a: int, b: int) -> bool:
        return any(a < r < b for r in self.rebinds.get(name, ()))


def _self_read_of(value: ast.expr) -> Optional[str]:
    """Attribute A when ``value`` reads an element out of ``self.A``
    (subscript, .get(), .pop())."""
    node = value
    if isinstance(node, ast.Call):
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in ("get", "pop"):
            node = func.value
        else:
            return None
    if isinstance(node, ast.Subscript):
        node = node.value
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name) and node.value.id == "self"):
        return node.attr
    return None


def _iter_self_attr(it: ast.expr) -> Optional[str]:
    """Attribute A when iterating ``self.A`` / ``self.A.values()`` /
    ``self.A.items()``."""
    node = it
    if isinstance(node, ast.Call):
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in ("values", "items"):
            node = func.value
        else:
            return None
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name) and node.value.id == "self"):
        return node.attr
    return None


def _record_mutation_target(facts: _FnFacts, node: ast.AST,
                            target: ast.expr, via: str) -> None:
    inner = target
    sub = False
    if isinstance(inner, ast.Subscript):
        inner = inner.value
        sub = True
    if isinstance(inner, ast.Name):
        if sub:
            facts.mutations.append((node.lineno, inner.id, "[...]",
                                    "subscript"))
        return
    root, attrs = _attr_chain(inner)
    if root is None or root == "self" or not attrs:
        return
    what = ".".join(attrs) + ("[...]" if sub else "")
    facts.mutations.append((node.lineno, root, what, via))


def _scan_function(fn_node: ast.AST) -> _FnFacts:
    facts = _FnFacts()

    # prepass: which locals are commit-event batches (passed by name to
    # _commit/publish)?
    event_lists: Set[str] = set()
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Call):
            func = node.func
            cname = (func.attr if isinstance(func, ast.Attribute)
                     else func.id if isinstance(func, ast.Name) else None)
            if cname in EVENT_SINK_NAMES:
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        event_lists.add(arg.id)

    for node in ast.walk(fn_node):
        if isinstance(node, ast.Assign):
            value = node.value
            for target in node.targets:
                _record_mutation_target(facts, node, target, "assign")
                # self.A = name retention
                if (isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"
                        and isinstance(value, ast.Name)):
                    facts.retains.append((node.lineno, target.attr, value.id))
                # self.A[k] = name retention
                if (isinstance(target, ast.Subscript)
                        and isinstance(target.value, ast.Attribute)
                        and isinstance(target.value.value, ast.Name)
                        and target.value.value.id == "self"
                        and isinstance(value, ast.Name)):
                    facts.retains.append((node.lineno, target.value.attr,
                                          value.id))
                if isinstance(target, ast.Name):
                    name = target.id
                    self_attr = _self_read_of(value)
                    if self_attr is not None:
                        facts.self_reads.append((node.lineno, name, self_attr))
                        continue
                    if (isinstance(value, ast.Subscript)
                            and isinstance(value.value, ast.Name)):
                        facts.alias[name] = value.value.id
                        continue
                    if _read_call(value):
                        facts.taints.append((node.lineno, name))
                    if isinstance(value, (ast.List, ast.Tuple)):
                        members = {e.id for e in value.elts
                                   if isinstance(e, ast.Name)}
                        if members:
                            facts.list_members[name] = members
                        if name in event_lists:
                            facts.event_appends.append((node.lineno,
                                                        _deep_names(value)))
                    facts.rebinds.setdefault(name, []).append(node.lineno)
                else:
                    for name in _target_names(target):
                        facts.rebinds.setdefault(name, []).append(node.lineno)
        elif isinstance(node, ast.AugAssign):
            _record_mutation_target(facts, node, node.target, "augassign")
            for name in _target_names(node.target):
                facts.rebinds.setdefault(name, []).append(node.lineno)
        elif isinstance(node, ast.Delete):
            for target in node.targets:
                _record_mutation_target(facts, node, target, "del")
        elif isinstance(node, ast.For):
            names = _target_names(node.target)
            self_attr = _iter_self_attr(node.iter)
            if self_attr is not None:
                # for v in self.A.values() / for k, v in self.A.items()
                picked = names[-1:] if names else []
                for name in picked:
                    facts.self_reads.append((node.lineno, name, self_attr))
                continue
            if isinstance(node.iter, ast.Name):
                for name in names:
                    facts.alias[name] = node.iter.id
                continue
            if _read_call(node.iter):
                for name in names:
                    facts.taints.append((node.lineno, name))
            for name in names:
                facts.rebinds.setdefault(name, []).append(node.lineno)
        elif isinstance(node, ast.Call):
            func = node.func
            kind = cname = None
            if isinstance(func, ast.Name):
                kind, cname = "name", func.id
            elif isinstance(func, ast.Attribute):
                if (isinstance(func.value, ast.Name)
                        and func.value.id == "self"):
                    kind = "self"
                else:
                    kind = "attr"
                cname = func.attr

            store_sink = _store_sink_name(node)
            raft_sink = _raft_sink_name(node)
            is_sink = store_sink is not None or raft_sink is not None
            if is_sink:
                escaped: Set[str] = set()
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    escaped |= _deep_names(arg)
                for name in list(escaped):
                    escaped |= facts.list_members.get(name, set())
                if store_sink is not None:
                    facts.sinks_store.append((node.lineno, store_sink,
                                              escaped))
                if raft_sink is not None:
                    facts.sinks_raft.append((node.lineno, raft_sink, escaped))

            if isinstance(func, ast.Attribute):
                root, attrs = _attr_chain(func)
                if func.attr in MUTATORS and root is not None and root != "self":
                    chain = attrs[:-1]      # drop the mutator itself
                    what = ".".join(chain + [func.attr])
                    facts.mutations.append((node.lineno, root, what, "mcall"))
                # self.A.append(name) retention
                if (func.attr in ("append", "add", "setdefault")
                        and isinstance(func.value, ast.Attribute)
                        and isinstance(func.value.value, ast.Name)
                        and func.value.value.id == "self"):
                    for arg in node.args:
                        if isinstance(arg, ast.Name):
                            facts.retains.append((node.lineno, func.value.attr,
                                                  arg.id))
                # event_batch.append((kind, obj)) escape
                if (func.attr in ("append", "extend")
                        and isinstance(func.value, ast.Name)
                        and func.value.id in event_lists):
                    names: Set[str] = set()
                    for arg in node.args:
                        names |= _deep_names(arg)
                    if names:
                        facts.event_appends.append((node.lineno, names))

            if kind is not None:
                rec = _CallRec(node.lineno, kind, cname, is_sink=is_sink)
                for i, arg in enumerate(node.args):
                    if isinstance(arg, ast.Name):
                        rec.pos.append((i, arg.id))
                    elif isinstance(arg, (ast.List, ast.Tuple)):
                        for e in arg.elts:
                            if isinstance(e, ast.Name):
                                rec.elems.append((i, e.id))
                for kw in node.keywords:
                    if kw.arg is not None and isinstance(kw.value, ast.Name):
                        rec.kws.append((kw.arg, kw.value.id))
                facts.calls.append(rec)
    return facts


# --- interprocedural mutation summaries ---------------------------------


def _facts_cache(ctx: AnalysisContext) -> Dict[FuncInfo, _FnFacts]:
    cache = getattr(ctx, "_ownership_facts", None)
    if cache is None:
        cache = ctx._ownership_facts = {}
    return cache


def _facts(ctx: AnalysisContext, fn: FuncInfo) -> _FnFacts:
    cache = _facts_cache(ctx)
    facts = cache.get(fn)
    if facts is None:
        facts = cache[fn] = _scan_function(fn.node)
    return facts


def _summaries(ctx: AnalysisContext) -> Dict[FuncInfo, Set[str]]:
    """fn -> parameter names it may attribute-mutate, directly or through
    any resolution candidate of its calls (fixpoint)."""
    cached = getattr(ctx, "_ownership_summaries", None)
    if cached is not None:
        return cached
    cg: CallGraph = ctx.callgraph
    summ: Dict[FuncInfo, Set[str]] = {}
    for fn in cg.functions:
        facts = _facts(ctx, fn)
        params = set(_params(fn.node))
        direct: Set[str] = set()
        for line, root, _what, _via in facts.mutations:
            resolved = facts.root(root)
            if resolved not in params:
                continue
            first_rebind = min(facts.rebinds.get(root, [line + 1]))
            if first_rebind < line:
                continue        # rebound (e.g. copied) before the mutation
            direct.add(resolved)
        summ[fn] = direct
    changed = True
    while changed:
        changed = False
        for fn in cg.functions:
            params = set(_params(fn.node))
            have = summ[fn]
            if params <= have:
                continue
            facts = _facts(ctx, fn)
            for rec in facts.calls:
                for argname in _mutated_args(rec, cg, fn, summ):
                    if argname in params and argname not in have:
                        have.add(argname)
                        changed = True
    ctx._ownership_summaries = summ
    return summ


def _mutated_args(rec: _CallRec, cg: CallGraph, caller: FuncInfo,
                  summ: Dict[FuncInfo, Set[str]]) -> Set[str]:
    """Argument names this call may mutate. Name-based resolution is an
    over-approximation, so when a call is ambiguous (several same-named
    candidates) a name counts only if EVERY candidate mutates that slot
    — one innocent namesake vetoes, which keeps cross-class collisions
    (e.g. an unrelated ``register``) from poisoning the summaries."""
    per: List[Set[str]] = []
    for callee in cg.resolve(caller, rec.kind, rec.name):
        callee_summ = summ.get(callee, set())
        cparams = _params(callee.node)
        names: Set[str] = set()
        if callee_summ:
            for i, argname in rec.pos + rec.elems:
                if i < len(cparams) and cparams[i] in callee_summ:
                    names.add(argname)
            for kwname, argname in rec.kws:
                if kwname in callee_summ:
                    names.add(argname)
        per.append(names)
    if not per:
        return set()
    out = per[0]
    for names in per[1:]:
        out &= names
    return out


# --- the rules ----------------------------------------------------------


def _mods_by_rel(ctx: AnalysisContext) -> Dict[str, Module]:
    return {mod.rel: mod for mod in ctx.modules}


def _escape_findings(ctx: AnalysisContext, rule_id: str, scope,
                     sink_lists, noun: str) -> List[Finding]:
    """Shared engine for store-escape-mutation / publish-after-mutate:
    flag mutations (direct or via a mutating callee) of names escaped to
    a sink earlier in the function."""
    cg: CallGraph = ctx.callgraph
    summ = _summaries(ctx)
    mods = _mods_by_rel(ctx)
    findings: List[Finding] = []
    seen: Set[Tuple] = set()

    def emit(mod, fn, line, detail, message):
        key = (rule_id, mod.rel, f"{mod.rel}:{fn.qualname}", detail)
        if key in seen or _suppressed(mod, line):
            return
        seen.add(key)
        findings.append(Finding(
            rule=rule_id, path=mod.rel, line=line, severity="error",
            message=message, context=f"{mod.rel}:{fn.qualname}",
            detail=detail))

    for fn in cg.functions:
        mod = mods.get(fn.module_rel)
        if mod is None or not scope(mod.rel):
            continue
        facts = _facts(ctx, fn)
        sinks = sink_lists(facts)
        if not sinks:
            continue
        for sline, label, names in sinks:
            for mline, root, what, via in facts.mutations:
                if mline <= sline:
                    continue
                if root not in names and facts.root(root) not in names:
                    continue
                if via == "mcall" and "." not in what and root in names:
                    # whole-container mutator on the batch list itself:
                    # the store iterates the list, it never retains it
                    continue
                if facts.rebound_between(root, sline, mline):
                    continue
                emit(mod, fn, mline, f"{root}@{label}->{what}",
                     f"'{root}' escaped to {label}() at line {sline} and "
                     f"is {noun} from then on; mutating '{root}.{what}' "
                     f"afterwards rewrites it — copy before mutating")
            for rec in facts.calls:
                if rec.line <= sline or rec.is_sink:
                    continue
                for root in _mutated_args(rec, cg, fn, summ):
                    if root not in names:
                        continue
                    if facts.rebound_between(root, sline, rec.line):
                        continue
                    emit(mod, fn, rec.line, f"{root}@{label}=>{rec.name}",
                         f"'{root}' escaped to {label}() at line {sline} "
                         f"and is {noun} from then on; passing it to "
                         f"{rec.name}() afterwards mutates it — copy "
                         f"before handing it off")
    return findings


@rule("store-escape-mutation",
      "structs handed to store upserts or raft propose/apply are shared "
      "history and must not be mutated afterwards")
def check_store_escape(ctx: AnalysisContext) -> List[Finding]:
    return _escape_findings(
        ctx, "store-escape-mutation",
        scope=lambda rel: in_scope(rel, OWNERSHIP_SCOPE),
        sink_lists=lambda f: f.sinks_store + f.sinks_raft,
        noun="shared store/raft-log history")


@rule("publish-after-mutate",
      "structs already appended to a commit-event batch must not be "
      "mutated before the batch publishes")
def check_publish_after_mutate(ctx: AnalysisContext) -> List[Finding]:
    return _escape_findings(
        ctx, "publish-after-mutate",
        scope=lambda rel: in_scope(rel, PUBLISH_SCOPE),
        sink_lists=lambda f: [(line, "events.append", names)
                              for line, names in f.event_appends],
        noun="referenced by the pending event batch")


@rule("read-mutate-no-copy",
      "store-read structs passed to mutating callees or container-mutated "
      "without an intervening copy")
def check_read_mutate(ctx: AnalysisContext) -> List[Finding]:
    cg: CallGraph = ctx.callgraph
    summ = _summaries(ctx)
    mods = _mods_by_rel(ctx)
    findings: List[Finding] = []
    seen: Set[Tuple] = set()

    def emit(mod, fn, line, name, tline, detail, how):
        key = ("read-mutate-no-copy", mod.rel, f"{mod.rel}:{fn.qualname}",
               detail)
        if key in seen or _suppressed(mod, line):
            return
        seen.add(key)
        findings.append(Finding(
            rule="read-mutate-no-copy", path=mod.rel, line=line,
            severity="error",
            message=(f"'{name}' was read from the state store (line {tline}) "
                     f"and {how} without an intervening copy — store rows "
                     "are shared across snapshots; copy.copy() first"),
            context=f"{mod.rel}:{fn.qualname}", detail=detail))

    for fn in cg.functions:
        mod = mods.get(fn.module_rel)
        if mod is None or not _analysis_scope(mod):
            continue
        facts = _facts(ctx, fn)
        if not facts.taints:
            continue
        taint_lines: Dict[str, List[int]] = {}
        for tline, name in facts.taints:
            taint_lines.setdefault(name, []).append(tline)

        def live_taint(name: str, line: int) -> Optional[int]:
            for tline in sorted(taint_lines.get(name, ()), reverse=True):
                if tline < line and not facts.rebound_between(name, tline,
                                                              line):
                    return tline
            return None

        # (b) container-mutator calls / keyed assigns through tainted
        # names — the attribute-assignment cases belong to the
        # intra-procedural shared-struct-mutation rule
        for mline, root, what, via in facts.mutations:
            if via not in ("mcall", "subscript"):
                continue
            tline = live_taint(root, mline)
            if tline is None:
                continue
            emit(mod, fn, mline, root, tline, f"{root}.{what}",
                 f"container-mutated ('{root}.{what}')")
        # (a) handed to a callee whose summary mutates that parameter
        for rec in facts.calls:
            muts = _mutated_args(rec, cg, fn, summ)
            for name in muts:
                tline = live_taint(name, rec.line)
                if tline is None:
                    continue
                emit(mod, fn, rec.line, name, tline, f"{name}=>{rec.name}",
                     f"passed to {rec.name}(), which mutates it")
    return findings


@rule("propose-retain-alias",
      "objects proposed into the raft log and retained on self must not "
      "be mutated through the retained alias")
def check_propose_retain(ctx: AnalysisContext) -> List[Finding]:
    mods = _mods_by_rel(ctx)
    findings: List[Finding] = []
    seen: Set[Tuple] = set()
    for mod in ctx.modules:
        if not in_scope(mod.rel, RETAIN_SCOPE):
            continue
        for cls in mod.tree.body:
            if not isinstance(cls, ast.ClassDef):
                continue
            methods = [(sub, _scan_function(sub)) for sub in cls.body
                       if isinstance(sub, (ast.FunctionDef,
                                           ast.AsyncFunctionDef))]
            # attributes that retain a proposed object
            retained: Dict[str, Tuple[str, str]] = {}
            for sub, facts in methods:
                proposed: Set[str] = set()
                for _line, _label, names in facts.sinks_raft:
                    proposed |= names
                if not proposed:
                    continue
                for _line, attr, name in facts.retains:
                    if name in proposed:
                        retained[attr] = (sub.name, name)
            if not retained:
                continue
            for sub, facts in methods:
                for bline, local, attr in facts.self_reads:
                    if attr not in retained:
                        continue
                    for mline, root, what, _via in facts.mutations:
                        if root != local or mline <= bline:
                            continue
                        if facts.rebound_between(local, bline, mline):
                            continue
                        if _suppressed(mod, mline):
                            continue
                        qual = f"{cls.name}.{sub.name}"
                        detail = f"self.{attr}->{local}.{what}"
                        key = (mod.rel, qual, detail)
                        if key in seen:
                            continue
                        seen.add(key)
                        src_m, src_n = retained[attr]
                        findings.append(Finding(
                            rule="propose-retain-alias", path=mod.rel,
                            line=mline, severity="error",
                            message=(f"'{local}' comes out of self.{attr}, "
                                     f"which retains objects proposed into "
                                     f"the raft log ({src_m}() retains "
                                     f"'{src_n}'); mutating "
                                     f"'{local}.{what}' rewrites replicated "
                                     "log history — copy before mutating"),
                            context=f"{mod.rel}:{qual}", detail=detail))
    return findings
