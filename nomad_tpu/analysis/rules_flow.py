"""nomadflow static prong: mutation→event completeness rules.

The device-resident incremental cluster state (ROADMAP) consumes the
broker's commit stream as THE source of truth for what changed — as
AllocSyncHub already does. That is only sound if the stream is a
*complete, ordered, keyed* function of store commits. These five
pure-AST rules prove the shape of that contract; the runtime half
(analysis/shadow.py) proves the values.

The table→topic map is derived, not hand-written: ``TOPIC_FOR_KIND``
(core/events.py) gives kind→topic, and every ``VersionedTable("<name>")``
binding in state/store.py gives attr→table. A table maps to a topic when
its dash-singularized name prefixes kinds of exactly one topic
("alloc_blocks" → "alloc-block-*" → Allocation); tables whose names
prefix no kind (volumes, secondary indexes, usage columns) carry no
delta obligation.

Rules (all suppressible with ``# san-ok: <why>``, never baselined):

``flow-mutation-without-delta`` — an FSM-reachable store mutator (the
raft/fsm.py MUTATIONS dispatch surface) whose call closure writes a
delta-consumed table but emits no event kind on that table's topic. A
closure that emits the ``restore`` sentinel is exempt: the broker turns
it into a full ring truncation, so every subscriber resyncs anyway.

``flow-publish-before-commit`` — (a) a function that publishes an event
and THEN runs the store mutation it describes: a woken subscriber can
snapshot before the commit and see stale state; (b) a commit
implementation that runs its listener fan-out before publishing the new
index.

``flow-delta-payload-narrowing`` — a dict-literal event payload that
omits a field some in-scope subscriber of that topic reads off the
payload (interprocedural: consumer field sets are collected per
subscribing module, ``getattr(payload, ...)`` and ``*.payload``
projections included).

``flow-resync-gap-unhandled`` — a consumer that calls
``Subscription.next_events`` without ever reading ``.truncated``
(gap-unchecked), or reads it but neither triggers a resync/snapshot
re-read nor acknowledges the flag (gap-unhandled). Returning the flag
to the caller (the ``events_after`` shape) counts as propagation.

``flow-unkeyed-delta`` — an event ring append carrying the literal
index 0 instead of a store generation: index-0 events sort before
everything in cross-shard merges and give cursors nothing to resume
from.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import CallGraph, FuncInfo
from .core import AnalysisContext, Finding, Module, in_scope, rule
from .rules_concurrency import _suppressed

# Producers (store mutators, the broker) live in state/core/raft;
# consumers of the stream additionally live in the API layer (the
# ndjson event stream), so the consumer-side rules scan there too.
FLOW_SCOPE = ("state", "core", "raft")
CONSUMER_SCOPE = ("state", "core", "raft", "api")

# Event kinds that invalidate EVERY topic: the broker truncates all
# rings on them, forcing each subscriber through its resync path, so a
# mutator emitting one owes no per-table deltas.
RESYNC_KINDS = frozenset({"restore"})

FLOW_RULES = (
    "flow-mutation-without-delta",
    "flow-publish-before-commit",
    "flow-delta-payload-narrowing",
    "flow-resync-gap-unhandled",
    "flow-unkeyed-delta",
)


# --- table→topic map -----------------------------------------------------

def build_topic_map(modules: List[Module]
                    ) -> Tuple[Dict[str, str], Dict[str, str]]:
    """-> (kind→topic, table_attr→topic), both derived from the ASTs."""
    kind_topic: Dict[str, str] = {}
    tables: Dict[str, str] = {}          # attr name -> table ctor name
    for mod in modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if (isinstance(tgt, ast.Name) and tgt.id == "TOPIC_FOR_KIND"
                        and isinstance(node.value, ast.Dict)):
                    for k, v in zip(node.value.keys, node.value.values):
                        if (isinstance(k, ast.Constant)
                                and isinstance(k.value, str)
                                and isinstance(v, ast.Constant)
                                and isinstance(v.value, str)):
                            kind_topic[k.value] = v.value
                elif (isinstance(tgt, ast.Attribute)
                        and isinstance(node.value, ast.Call)):
                    fn = node.value.func
                    ctor = (fn.id if isinstance(fn, ast.Name)
                            else fn.attr if isinstance(fn, ast.Attribute)
                            else None)
                    if (ctor == "VersionedTable" and node.value.args
                            and isinstance(node.value.args[0], ast.Constant)
                            and isinstance(node.value.args[0].value, str)):
                        tables[tgt.attr] = node.value.args[0].value
    table_topic: Dict[str, str] = {}
    for attr, tname in tables.items():
        singular = tname[:-1] if tname.endswith("s") else tname
        prefix = singular.replace("_", "-") + "-"
        topics = {t for k, t in kind_topic.items() if k.startswith(prefix)}
        if len(topics) == 1:
            table_topic[attr] = topics.pop()
    return kind_topic, table_topic


def _mutation_names(modules: List[Module]) -> Set[str]:
    """Names in module-level MUTATIONS set literals (the FSM dispatch
    surface, raft/fsm.py)."""
    names: Set[str] = set()
    for mod in modules:
        for stmt in mod.tree.body:
            if not isinstance(stmt, ast.Assign):
                continue
            for tgt in stmt.targets:
                if (isinstance(tgt, ast.Name) and tgt.id == "MUTATIONS"
                        and isinstance(stmt.value, ast.Set)):
                    for elt in stmt.value.elts:
                        if (isinstance(elt, ast.Constant)
                                and isinstance(elt.value, str)):
                            names.add(elt.value)
    return names


def _scoped(ctx: AnalysisContext, subdirs) -> List[Module]:
    return [m for m in ctx.modules if in_scope(m.rel, subdirs)]


def _nested_def_nodes(fn_node: ast.AST) -> Set[int]:
    """ids of every node inside a def/lambda nested under fn_node —
    deferred code, not part of fn's own execution order."""
    inner: Set[int] = set()
    for sub in ast.walk(fn_node):
        if sub is fn_node:
            continue
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            inner.update(id(n) for n in ast.walk(sub))
    return inner


def _call_name(node: ast.Call) -> Optional[str]:
    func = node.func
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


# --- rule 1: mutation-without-delta --------------------------------------

def _table_writes(fn_node: ast.AST, table_topic: Dict[str, str]):
    """(table_attr, call node) for every ``*._table.put/.delete`` in the
    subtree — attribute-chain writes (``store._nodes.put``) included."""
    out = []
    for node in ast.walk(fn_node):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if (isinstance(func, ast.Attribute)
                and func.attr in ("put", "delete")
                and isinstance(func.value, ast.Attribute)
                and func.value.attr in table_topic):
            out.append((func.value.attr, node))
    return out


def _emitted_kinds(closure, kind_topic: Dict[str, str]) -> Set[str]:
    """Every string constant in the closure that names an event kind —
    deliberately over-approximate (call-site literals like
    ``self._update_node(id, "node-drain", mut)`` count)."""
    out: Set[str] = set()
    for fn in closure:
        for node in ast.walk(fn.node):
            if (isinstance(node, ast.Constant) and isinstance(node.value, str)
                    and (node.value in kind_topic
                         or node.value in RESYNC_KINDS)):
                out.add(node.value)
    return out


@rule("flow-mutation-without-delta",
      "an FSM-reachable store mutator writes a delta-consumed table "
      "without publishing on that table's topic")
def check_mutation_without_delta(ctx: AnalysisContext) -> List[Finding]:
    modules = _scoped(ctx, FLOW_SCOPE)
    kind_topic, table_topic = build_topic_map(modules)
    if not table_topic:
        return []
    cg = CallGraph(modules)
    names = _mutation_names(modules)
    roots = [f for f in cg.functions if f.name in names]
    by_rel = {m.rel: m for m in modules}
    findings: List[Finding] = []
    for root in sorted(roots, key=lambda f: (f.module_rel, f.qualname)):
        closure = cg.reachable([root])
        kinds = _emitted_kinds(closure, kind_topic)
        if kinds & RESYNC_KINDS:
            continue
        covered = {kind_topic[k] for k in kinds if k in kind_topic}
        seen: Set[str] = set()
        for fn in sorted(closure, key=lambda f: (f.module_rel, f.qualname)):
            mod = by_rel[fn.module_rel]
            for table_attr, call in _table_writes(fn.node, table_topic):
                topic = table_topic[table_attr]
                if topic in covered or table_attr in seen:
                    continue
                if _suppressed(mod, call.lineno):
                    seen.add(table_attr)
                    continue
                seen.add(table_attr)
                findings.append(Finding(
                    rule="flow-mutation-without-delta",
                    path=fn.module_rel, line=call.lineno, severity="error",
                    message=(f"store mutator '{root.name}' writes "
                             f"{table_attr} (topic {topic}) but its call "
                             f"closure publishes no {topic} event — delta "
                             "consumers (AllocSyncHub, the shadow store, "
                             "the incremental tensor state) silently "
                             "diverge; emit a mapped kind or the "
                             "'restore' resync sentinel"),
                    context=f"{root.module_rel}:{root.qualname}",
                    detail=f"{root.name}:{table_attr}"))
    return findings


# --- rule 2: publish-before-commit ---------------------------------------

@rule("flow-publish-before-commit",
      "event published before the store mutation/index bump that makes "
      "the state visible")
def check_publish_before_commit(ctx: AnalysisContext) -> List[Finding]:
    modules = _scoped(ctx, FLOW_SCOPE)
    cg = CallGraph(modules)
    mutators = _mutation_names(modules) | {"_commit"}
    by_rel = {m.rel: m for m in modules}
    findings: List[Finding] = []
    for fn in sorted(cg.functions, key=lambda f: (f.module_rel, f.qualname)):
        mod = by_rel[fn.module_rel]
        inner = _nested_def_nodes(fn.node)

        # shape (a): .publish(...) textually before a store mutation in
        # the same (non-deferred) body
        publishes = []
        mut_calls = []
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call) or id(node) in inner:
                continue
            name = _call_name(node)
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "publish"):
                publishes.append(node)
            elif name in mutators:
                mut_calls.append((node.lineno, name))
        for pub in publishes:
            later = [n for ln, n in mut_calls if ln > pub.lineno]
            if not later or _suppressed(mod, pub.lineno):
                continue
            findings.append(Finding(
                rule="flow-publish-before-commit",
                path=fn.module_rel, line=pub.lineno, severity="error",
                message=(f"event published before the '{later[0]}' store "
                         "mutation in the same function — a woken "
                         "subscriber can snapshot stale state; commit "
                         "first, then publish"),
                context=f"{fn.module_rel}:{fn.qualname}",
                detail=f"publish-before:{later[0]}"))

        # shape (b): commit implementation fanning out to listeners
        # before publishing the new index
        index_lines = [n.lineno for n in ast.walk(fn.node)
                       if isinstance(n, ast.Assign)
                       and any(isinstance(t, ast.Attribute)
                               and t.attr == "_index"
                               and isinstance(t.value, ast.Name)
                               and t.value.id == "self"
                               for t in n.targets)]
        loop_lines = []
        for n in ast.walk(fn.node):
            if not isinstance(n, ast.For):
                continue
            it = n.iter
            name = (it.attr if isinstance(it, ast.Attribute)
                    else it.id if isinstance(it, ast.Name) else "")
            if "listener" in name and any(isinstance(c, ast.Call)
                                          for b in n.body
                                          for c in ast.walk(b)):
                loop_lines.append(n.lineno)
        if index_lines and loop_lines \
                and min(loop_lines) < min(index_lines) \
                and not _suppressed(mod, min(loop_lines)):
            findings.append(Finding(
                rule="flow-publish-before-commit",
                path=fn.module_rel, line=min(loop_lines), severity="error",
                message=("commit listeners run before the index is "
                         "published — a listener-woken reader blocks on "
                         "an index the store claims not to have"),
                context=f"{fn.module_rel}:{fn.qualname}",
                detail="listeners-before-index"))
    return findings


# --- rule 3: delta-payload-narrowing -------------------------------------

def _subscribed_topics(tree: ast.AST) -> Set[str]:
    """Topic keys of every ``.subscribe({dict literal})`` in the tree."""
    topics: Set[str] = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "subscribe" and node.args
                and isinstance(node.args[0], ast.Dict)):
            for k in node.args[0].keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    topics.add(k.value)
    return topics


def _payload_field_reads(tree: ast.AST) -> Set[str]:
    """Fields projected off event payloads anywhere in the tree:
    ``x = ev.payload; x.f``, ``ev.payload.f``, and
    ``getattr(<payload-derived>, "f", ...)``. Function parameters
    literally named ``payload`` are payload-derived (the helper-call
    convention)."""
    derived: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for a in node.args.args:
                if a.arg == "payload":
                    derived.add("payload")
        elif (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Attribute)
                and node.value.attr == "payload"):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    derived.add(t.id)

    def _is_derived(expr: ast.expr) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id in derived
        return isinstance(expr, ast.Attribute) and expr.attr == "payload"

    fields: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and _is_derived(node.value):
            fields.add(node.attr)
        elif (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id == "getattr" and len(node.args) >= 2
                and isinstance(node.args[1], ast.Constant)
                and isinstance(node.args[1].value, str)
                and _is_derived(node.args[0])):
            fields.add(node.args[1].value)
    return fields


@rule("flow-delta-payload-narrowing",
      "a dict-literal event payload omits fields a subscriber of that "
      "topic reads")
def check_payload_narrowing(ctx: AnalysisContext) -> List[Finding]:
    consumers = _scoped(ctx, CONSUMER_SCOPE)
    producers = _scoped(ctx, FLOW_SCOPE)
    kind_topic, _ = build_topic_map(consumers)

    fields_by_topic: Dict[str, Set[str]] = {}
    for mod in consumers:
        topics = _subscribed_topics(mod.tree)
        if not topics:
            continue
        fields = _payload_field_reads(mod.tree)
        for t in topics:
            fields_by_topic.setdefault(t, set()).update(fields)

    def _needed(topic: str) -> Set[str]:
        return (fields_by_topic.get(topic, set())
                | fields_by_topic.get("*", set()))

    findings: List[Finding] = []
    for mod in producers:
        sites = []          # (topic, dict node)
        for node in ast.walk(mod.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "publish"
                    and len(node.args) >= 3
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and isinstance(node.args[2], ast.Dict)):
                sites.append((node.args[0].value, node.args[2]))
            elif (isinstance(node, ast.Tuple) and len(node.elts) == 2
                    and isinstance(node.elts[0], ast.Constant)
                    and isinstance(node.elts[0].value, str)
                    and node.elts[0].value in kind_topic
                    and isinstance(node.elts[1], ast.Dict)):
                sites.append((kind_topic[node.elts[0].value], node.elts[1]))
        for topic, payload in sites:
            needed = _needed(topic)
            if not needed:
                continue
            keys = {k.value for k in payload.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
            if any(k is None for k in payload.keys):
                continue    # **spread: keys unknowable, stay silent
            if _suppressed(mod, payload.lineno):
                continue
            for fieldname in sorted(needed - keys):
                findings.append(Finding(
                    rule="flow-delta-payload-narrowing",
                    path=mod.rel, line=payload.lineno, severity="error",
                    message=(f"payload for topic {topic} omits '{fieldname}'"
                             " — a subscriber of this topic reads it off "
                             "the payload and will see its default "
                             "instead of the value"),
                    context=mod.enclosing_function(payload),
                    detail=f"narrowed:{topic}:{fieldname}"))
    return findings


# --- rule 4: resync-gap-unhandled ----------------------------------------

@rule("flow-resync-gap-unhandled",
      "a subscription consumer ignores or fails to act on the ring "
      "truncation flag")
def check_resync_gap(ctx: AnalysisContext) -> List[Finding]:
    modules = _scoped(ctx, CONSUMER_SCOPE)
    cg = CallGraph(modules)
    by_rel = {m.rel: m for m in modules}
    findings: List[Finding] = []
    for fn in sorted(cg.functions, key=lambda f: (f.module_rel, f.qualname)):
        next_calls = [n for n in ast.walk(fn.node)
                      if isinstance(n, ast.Call)
                      and isinstance(n.func, ast.Attribute)
                      and n.func.attr == "next_events"]
        if not next_calls:
            continue
        mod = by_rel[fn.module_rel]
        in_return: Set[int] = set()
        for n in ast.walk(fn.node):
            if isinstance(n, ast.Return):
                in_return.update(id(c) for c in ast.walk(n))
        reads = [n for n in ast.walk(fn.node)
                 if isinstance(n, ast.Attribute) and n.attr == "truncated"
                 and isinstance(n.ctx, ast.Load)]
        site = next_calls[0]
        if not reads:
            if not _suppressed(mod, site.lineno):
                findings.append(Finding(
                    rule="flow-resync-gap-unhandled",
                    path=fn.module_rel, line=site.lineno, severity="error",
                    message=("next_events consumer never reads "
                             ".truncated — a lapped ring silently drops "
                             "deltas and this consumer's view diverges "
                             "forever; check the flag and resync from a "
                             "snapshot"),
                    context=f"{fn.module_rel}:{fn.qualname}",
                    detail="gap-unchecked"))
            continue
        if all(id(r) in in_return for r in reads):
            continue        # propagated to the caller (events_after shape)
        acks = [n for n in ast.walk(fn.node)
                if (isinstance(n, ast.Assign)
                    and any(isinstance(t, ast.Attribute)
                            and ("resync" in t.attr
                                 or t.attr == "truncated")
                            for t in n.targets))
                or (isinstance(n, ast.Call)
                    and any(tok in (_call_name(n) or "")
                            for tok in ("resync", "snapshot", "restore",
                                        "rebuild")))]
        if not acks and not _suppressed(mod, reads[0].lineno):
            findings.append(Finding(
                rule="flow-resync-gap-unhandled",
                path=fn.module_rel, line=reads[0].lineno, severity="error",
                message=("truncation flag read but never acted on — set "
                         "the resync flag / re-read a snapshot (and clear "
                         ".truncated) so the gap is actually healed"),
                context=f"{fn.module_rel}:{fn.qualname}",
                detail="gap-unhandled"))
    return findings


# --- rule 5: unkeyed-delta -----------------------------------------------

@rule("flow-unkeyed-delta",
      "event ring append carries literal index 0 instead of a store "
      "generation")
def check_unkeyed_delta(ctx: AnalysisContext) -> List[Finding]:
    modules = _scoped(ctx, FLOW_SCOPE)
    findings: List[Finding] = []
    for mod in modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            zero_at = None
            if name == "_publish_shard":
                if (len(node.args) >= 3
                        and isinstance(node.args[2], ast.Constant)
                        and node.args[2].value == 0):
                    zero_at = "_publish_shard"
            elif name == "Event":
                if (len(node.args) >= 2
                        and isinstance(node.args[1], ast.Constant)
                        and node.args[1].value == 0):
                    zero_at = "Event"
            if zero_at is None:
                for kw in node.keywords:
                    if (name in ("_publish_shard", "Event")
                            and kw.arg == "index"
                            and isinstance(kw.value, ast.Constant)
                            and kw.value.value == 0):
                        zero_at = name
            if zero_at is None or _suppressed(mod, node.lineno):
                continue
            findings.append(Finding(
                rule="flow-unkeyed-delta",
                path=mod.rel, line=node.lineno, severity="error",
                message=(f"{zero_at} called with literal index 0 — "
                         "index-0 events sort before every commit in "
                         "cross-shard merges and leave cursors nothing "
                         "to resume from; stamp the last committed "
                         "store index"),
                context=mod.enclosing_function(node),
                detail=f"index-0:{zero_at}"))
    return findings
