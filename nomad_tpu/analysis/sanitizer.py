"""nomadsan runtime prong: instrumented locks + Eraser-style lockset.

The static rules (rules_concurrency.py) reason about names; this module
watches the real interleavings. Enabled via ``NOMAD_TPU_SAN=1`` (the
pytest plugin in tests/conftest.py calls :func:`install` before any
nomad_tpu module is imported), it

- wraps ``threading.Lock``/``threading.RLock`` construction so every
  lock records per-thread acquisition order into a global lock-order
  graph; acquiring B while holding A when a B->..->A path already exists
  anywhere in the run is a potential-deadlock *inversion* and is
  recorded as a violation (the dynamic analogue of the static
  ``lock-order-cycle`` rule — it needs no unlucky interleaving, only
  that both orders ever happen);
- implements an Eraser-style lockset checker (Savage et al. '97) for
  objects whose classes opt in via the :func:`sanitized` decorator
  (StateStore, EvalBroker, PlanQueue, DeploymentWatcher): each field
  starts *exclusive* to its first-writing thread; on the first write
  from a second thread it turns *shared* and its candidate lockset is
  initialized to the locks that thread holds; every later write
  intersects the candidate set with the writer's held locks, and an
  empty set means two threads mutate the field with no common lock —
  a write/write race — recorded as a violation.

Known soundness limits (documented, deliberate):

- only attribute REBINDS are seen (``self.x = ...`` through the wrapped
  ``__setattr__``); interior container mutation (``self.d[k] = v``) is
  invisible — the static ``shared-mutation-unlocked`` rule covers those
  sites by name;
- reads are not tracked (read/write races need ``__getattribute__``
  interception, which is far outside the <2x overhead budget);
- the lockset state machine ignores happens-before edges other than
  "same thread", so a field handed off through a join/queue can be a
  false positive — suppress per-field with ``_nomadsan_exempt``.

Violations never raise at the access site (raising inside an arbitrary
``acquire`` would corrupt the program under test); they accumulate in
``Sanitizer.violations`` and the pytest plugin fails the run at session
end. Tests can build private :class:`Sanitizer` instances so assertions
don't pollute the global run state.
"""

from __future__ import annotations

import _thread
import itertools
import sys
import threading
import traceback
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

_REAL_LOCK = _thread.allocate_lock     # un-patchable originals
_REAL_RLOCK = threading.RLock

_SKIP_FILES = (__file__, "threading.py", "queue.py")


def _call_site(extra_skip: int = 0) -> str:
    """file:line of the nearest frame outside sanitizer/threading."""
    f = sys._getframe(2 + extra_skip)
    while f is not None:
        fn = f.f_code.co_filename
        if not fn.endswith(_SKIP_FILES):
            return f"{fn}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


@dataclass
class Violation:
    kind: str            # "lock-order-inversion" | "lockset"
    message: str
    stack: List[str] = field(default_factory=list)

    def render(self) -> str:
        return f"[{self.kind}] {self.message}"


class Sanitizer:
    """One lock-order graph + lockset state space. The module-level
    GLOBAL instance is what install()/the @sanitized decorator feed;
    tests may build private instances."""

    def __init__(self):
        self.active = False
        # internal bookkeeping lock MUST be a raw lock: an instrumented
        # one would recurse into this class
        self._ilock = _REAL_LOCK()
        self._tls = threading.local()
        self._serials = itertools.count(1)
        self._labels: Dict[int, str] = {}            # serial -> creation site
        self._adj: Dict[int, Set[int]] = {}          # serial -> acquired-after set
        self._edge_sites: Dict[Tuple[int, int], str] = {}
        self._inversions_seen: Set[frozenset] = set()
        self._lockset_seen: Set[Tuple[str, str]] = set()
        self.violations: List[Violation] = []

    # -- lock factories ------------------------------------------------

    def Lock(self):
        return _SanLock(self, _REAL_LOCK())

    def RLock(self):
        return _SanRLock(self, _REAL_RLOCK())

    # -- global patching ----------------------------------------------

    def install(self) -> None:
        """Patch threading.Lock/RLock so every lock created from here on
        (including queue.Queue mutexes and Condition/Event internals,
        which look the factories up at call time) is instrumented."""
        if self.active:
            return
        self.active = True
        threading.Lock = self.Lock          # type: ignore[assignment]
        threading.RLock = self.RLock        # type: ignore[assignment]

    def uninstall(self) -> None:
        if not self.active:
            return
        self.active = False
        threading.Lock = _thread.allocate_lock  # type: ignore[assignment]
        threading.RLock = _REAL_RLOCK           # type: ignore[assignment]

    # -- per-thread held stack ----------------------------------------

    def _held(self) -> List[int]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def held_serials(self) -> List[int]:
        """Introspection for tests: serials this thread currently holds."""
        return list(self._held())

    def _note_acquire(self, serial: int) -> None:
        held = self._held()
        if serial in held:          # reentrant RLock re-acquire: no edges
            held.append(serial)
            return
        for outer in held:
            self._add_edge(outer, serial)
        held.append(serial)

    def _note_release(self, serial: int) -> None:
        held = self._held()
        # release the most recent acquisition (tolerates Condition
        # protocol asymmetries)
        for i in range(len(held) - 1, -1, -1):
            if held[i] == serial:
                del held[i]
                return

    def _note_release_all(self, serial: int) -> None:
        """Condition.wait fully releases an RLock regardless of depth."""
        self._tls.held = [s for s in self._held() if s != serial]

    # -- lock-order graph ---------------------------------------------

    def _add_edge(self, a: int, b: int) -> None:
        with self._ilock:
            succ = self._adj.setdefault(a, set())
            if b in succ:
                return
            succ.add(b)
            site = _call_site(1)
            self._edge_sites[(a, b)] = site
            # new edge a->b: a cycle exists iff a is reachable from b
            path = self._find_path(b, a)
        if path is not None:
            self._report_inversion(a, b, site, path)

    def _find_path(self, src: int, dst: int) -> Optional[List[int]]:
        """DFS under _ilock; returns node path src..dst or None."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._adj.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def _report_inversion(self, a: int, b: int, site: str,
                          path: List[int]) -> None:
        key = frozenset((a, b))
        with self._ilock:
            if key in self._inversions_seen:
                return
            self._inversions_seen.add(key)
            cycle = [self._label(b)] + [self._label(n) for n in path[1:]]
            other = self._edge_sites.get((path[0], path[1] if len(path) > 1
                                          else a), "?")
            v = Violation(
                "lock-order-inversion",
                f"acquired {self._label(b)} while holding {self._label(a)} "
                f"at {site}, but the reverse order exists "
                f"(cycle: {' -> '.join(cycle + [cycle[0]])}; "
                f"first reverse edge at {other})",
                stack=traceback.format_stack()[:-3])
            self.violations.append(v)

    def _label(self, serial: int) -> str:
        return f"lock#{serial}@{self._labels.get(serial, '?')}"

    # -- Eraser lockset ------------------------------------------------

    def sanitized(self, cls):
        """Class decorator: route attribute rebinds through the lockset
        state machine. Near-zero cost while inactive (one flag test)."""
        orig_setattr = cls.__setattr__
        san = self

        def __setattr__(obj, name, value):
            if san.active and not name.startswith("_nomadsan"):
                san._record_write(obj, name)
            orig_setattr(obj, name, value)

        cls.__setattr__ = __setattr__
        cls._nomadsan_watched = True
        return cls

    def _record_write(self, obj, name: str) -> None:
        if name in getattr(obj, "_nomadsan_exempt", ()):
            return
        tid = _thread.get_ident()
        held = frozenset(self._held())
        with self._ilock:
            try:
                fields = object.__getattribute__(obj, "_nomadsan_fields")
            except AttributeError:
                fields = {}
                object.__setattr__(obj, "_nomadsan_fields", fields)
            st = fields.get(name)
            if st is None:
                fields[name] = {"tid": tid, "lockset": None}
                return
            if st["lockset"] is None:       # exclusive phase
                if st["tid"] == tid:
                    return
                st["lockset"] = set(held)   # first shared write
            else:
                st["lockset"] &= held
            if st["lockset"]:
                return
            key = (type(obj).__name__, name)
            if key in self._lockset_seen:
                return
            self._lockset_seen.add(key)
            v = Violation(
                "lockset",
                f"{key[0]}.{name} is written by multiple threads with no "
                f"common lock held (second writer at {_call_site(1)}) — "
                "write/write race",
                stack=traceback.format_stack()[:-3])
        self.violations.append(v)

    # -- reporting -----------------------------------------------------

    def check(self) -> None:
        """Raise if any violation was recorded (stress tests call this
        directly; the pytest plugin prefers a session-end report)."""
        if self.violations:
            raise AssertionError(
                "nomadsan violations:\n"
                + "\n".join(v.render() for v in self.violations))

    def report(self) -> str:
        lines = [f"nomadsan: {len(self.violations)} violation(s)"]
        for v in self.violations:
            lines.append("  " + v.render())
        return "\n".join(lines)


class _SanLockBase:
    """Shared instrumentation shell. Everything not overridden delegates
    to the real lock, so Condition's duck probes keep working."""

    _reentrant = False

    def __init__(self, san: Sanitizer, inner):
        self._san = san
        self._inner = inner
        self._serial = next(san._serials)
        san._labels[self._serial] = _call_site()

    def acquire(self, blocking=True, timeout=-1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._san._note_acquire(self._serial)
        return ok

    def release(self):
        self._inner.release()
        self._san._note_release(self._serial)

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc):
        self.release()

    def locked(self):
        return self._inner.locked()

    def _at_fork_reinit(self):
        # os.fork/register_at_fork protocol (concurrent.futures.thread
        # registers its shutdown lock); the child starts with one thread
        self._inner._at_fork_reinit()

    def __repr__(self):
        return (f"<nomadsan {type(self).__name__} #{self._serial} "
                f"wrapping {self._inner!r}>")


class _SanLock(_SanLockBase):
    pass


class _SanRLock(_SanLockBase):
    """Instrumented RLock, including the private Condition protocol
    (_release_save/_acquire_restore/_is_owned) so ``Condition(rlock)``
    and ``Condition()`` both stay correct: wait() releases the lock for
    real, and the held-stack must reflect that or every post-wait
    acquisition would record phantom edges."""

    _reentrant = True

    def _release_save(self):
        self._san._note_release_all(self._serial)
        return self._inner._release_save()

    def _acquire_restore(self, state):
        self._inner._acquire_restore(state)
        self._san._note_acquire(self._serial)

    def _is_owned(self):
        return self._inner._is_owned()

    def locked(self):
        # 3.12 RLock.locked(); fall back to ownership probe on older runtimes
        probe = getattr(self._inner, "locked", None)
        if probe is not None:
            return probe()
        return self._inner._is_owned()


# -- module-level surface (what production code + conftest import) ------

GLOBAL = Sanitizer()


def install() -> None:
    GLOBAL.install()


def uninstall() -> None:
    GLOBAL.uninstall()


def enabled() -> bool:
    return GLOBAL.active


def sanitized(cls):
    """Opt a class into the global lockset checker. Applied to the
    control plane's shared-state owners (StateStore, EvalBroker,
    PlanQueue, DeploymentWatcher); inert unless install() ran."""
    return GLOBAL.sanitized(cls)


def violations() -> List[Violation]:
    return list(GLOBAL.violations)


def check() -> None:
    GLOBAL.check()
