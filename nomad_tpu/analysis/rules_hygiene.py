"""Hygiene rules: silent-except, lock-order, shared-struct-mutation.

`silent-except` — an `except Exception: pass` in the raft/state/scheduler
layers converts a correctness bug into an invisible no-op (a dropped
reconcile tick, a swallowed apply error). Broad handlers must at least
log before dropping.

`lock-order` — the package holds ~43 lock sites; two code paths taking
the same pair of locks in opposite orders is a deadlock waiting for the
right interleaving. The rule records every nested `with <lock>` pair
per function and flags pairs observed in both orders anywhere in the
analyzed tree.

`shared-struct-mutation` — StateStore reads return the live stored row
(go-memdb contract in the reference): mutating one in place corrupts
MVCC history for every open snapshot. Rows must be copied
(`copy.copy(...)`) before mutation; this rule taints locals bound from
store read calls and flags attribute/keyed assignment through them.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from .core import AnalysisContext, Finding, Module, in_scope, rule

# --- silent-except -----------------------------------------------------

EXCEPT_SCOPE = ("raft", "state", "scheduler")
LOG_TOKENS = ("log", "debug", "info", "warn", "error", "exception",
              "print", "record")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    for node in ([t.elts if isinstance(t, ast.Tuple) else [t]][0]):
        if isinstance(node, ast.Attribute):
            names.append(node.attr)
        elif isinstance(node, ast.Name):
            names.append(node.id)
    return any(n in ("Exception", "BaseException") for n in names)


def _has_log_or_raise(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = ""
            if isinstance(node.func, ast.Attribute):
                name = node.func.attr
            elif isinstance(node.func, ast.Name):
                name = node.func.id
            if any(tok in name.lower() for tok in LOG_TOKENS):
                return True
    return False


def _is_pass_shaped(handler: ast.ExceptHandler) -> bool:
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            continue
        if isinstance(stmt, ast.Return) and (
                stmt.value is None or isinstance(stmt.value, ast.Constant)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue
        return False
    return True


@rule("silent-except",
      "broad exception handlers in raft/state/scheduler must log "
      "before dropping the error")
def check_silent_except(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for mod in ctx.modules:
        if not in_scope(mod.rel, EXCEPT_SCOPE):
            continue
        per_context: Dict[str, int] = {}
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node):
                continue
            if _has_log_or_raise(node) or not _is_pass_shaped(node):
                continue
            context = f"{mod.rel}:{mod.enclosing_function(node)}"
            ordinal = per_context.get(context, 0)
            per_context[context] = ordinal + 1
            findings.append(Finding(
                rule="silent-except", path=mod.rel, line=node.lineno,
                severity="warning",
                message=("broad except silently drops the error — add at "
                         "least a debug-level log line"),
                context=context, detail=f"silent:{ordinal}"))
    return findings


# --- lock-order --------------------------------------------------------

LOCK_NAME_TOKENS = ("lock", "cond", "mutex", "sem")


def _lock_name(expr: ast.expr) -> str:
    """Dotted text of a lock-ish `with` context expr, or ""."""
    parts = []
    node = expr
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    else:
        return ""
    parts.reverse()
    if not any(tok in parts[-1].lower() for tok in LOCK_NAME_TOKENS):
        return ""
    if parts[0] == "self":
        parts = parts[1:]
    return ".".join(parts)


class _LockVisitor(ast.NodeVisitor):
    def __init__(self):
        self.stack: List[str] = []
        self.pairs: List[Tuple[str, str, int]] = []  # (outer, inner, line)

    def visit_With(self, node: ast.With):
        acquired = []
        for item in node.items:
            name = _lock_name(item.context_expr)
            if name:
                for outer in self.stack + acquired:
                    if outer != name:
                        self.pairs.append((outer, name, node.lineno))
                acquired.append(name)
        self.stack.extend(acquired)
        self.generic_visit(node)
        if acquired:
            del self.stack[-len(acquired):]

    def visit_FunctionDef(self, node):
        pass  # closures run later, outside this lock scope; walked separately

    visit_AsyncFunctionDef = visit_FunctionDef


@rule("lock-order",
      "lock pairs must be acquired in one consistent order everywhere")
def check_lock_order(ctx: AnalysisContext) -> List[Finding]:
    sites: Dict[Tuple[str, str], List[Tuple[Module, str, int]]] = {}
    for mod in ctx.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            visitor = _LockVisitor()
            for stmt in node.body:
                visitor.visit(stmt)
            for outer, inner, line in visitor.pairs:
                sites.setdefault((outer, inner), []).append(
                    (mod, f"{mod.rel}:{mod.enclosing_function(node)}", line))
    findings: List[Finding] = []
    for (outer, inner), occurrences in sorted(sites.items()):
        if (inner, outer) not in sites or (outer, inner) < (inner, outer):
            continue  # consistent, or report each conflicting pair once
        other = sites[(inner, outer)][0]
        for mod, context, line in occurrences + sites[(inner, outer)]:
            findings.append(Finding(
                rule="lock-order", path=mod.rel, line=line,
                severity="error",
                message=(f"locks '{outer}' and '{inner}' are acquired in "
                         f"both orders (other order at {other[1]}) — "
                         "deadlock risk; pick one global order"),
                context=context, detail=f"{outer}<->{inner}"))
    return findings


# --- shared-struct-mutation --------------------------------------------

READ_METHODS = {
    # StateStore / table internals
    "get_latest", "_latest_alloc", "iterate",
    # StateSnapshot read surface
    "node_by_id", "nodes", "ready_nodes_in_pool",
    "job_by_id", "jobs", "job_version", "job_versions",
    "eval_by_id", "evals", "evals_by_job",
    "alloc_by_id", "allocs", "alloc_blocks",
    "allocs_by_node", "allocs_by_node_terminal",
    "allocs_by_job", "allocs_by_eval",
    "deployments", "deployment_by_id", "deployments_by_job",
    "latest_deployment_by_job",
    "acl_policy", "acl_policies", "acl_token_by_accessor",
    "acl_token_by_secret", "acl_tokens", "acl_role", "acl_roles",
    "one_time_token", "scheduler_configuration",
    "auth_method", "auth_methods", "binding_rule", "binding_rules",
    "variable", "variables", "volume_by_id", "volumes",
    "service_registrations", "service_by_name",
    "node_pool", "node_pools", "namespace", "namespaces",
    "node_usage", "node_dev_usage",
}
UNWRAP_CALLS = ("list", "tuple", "sorted", "reversed", "iter", "next")


def _read_call(expr: ast.expr) -> bool:
    """True if `expr` evaluates to object(s) owned by the store."""
    if isinstance(expr, ast.Call):
        func = expr.func
        if isinstance(func, ast.Attribute) and func.attr in READ_METHODS:
            return True
        if (isinstance(func, ast.Name) and func.id in UNWRAP_CALLS
                and expr.args):
            return _read_call(expr.args[0])
    return False


def _target_names(target: ast.expr) -> List[str]:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out = []
        for elt in target.elts:
            out.extend(_target_names(elt))
        return out
    return []


class _TaintVisitor(ast.NodeVisitor):
    """Source-order walk of one function: taints locals bound from store
    reads, clears them on any other rebind (copy.copy included), flags
    attribute / keyed-attribute stores through tainted names."""

    def __init__(self, mod: Module, qual: str):
        self.mod = mod
        self.qual = qual
        self.tainted: Dict[str, int] = {}   # name -> read line
        self.findings: List[Finding] = []

    def _flag(self, node: ast.AST, name: str, what: str):
        self.findings.append(Finding(
            rule="shared-struct-mutation", path=self.mod.rel,
            line=node.lineno, severity="error",
            message=(f"{what} on '{name}' read from the state store "
                     f"(line {self.tainted[name]}) — store rows are "
                     "shared across snapshots; copy.copy() before "
                     "mutating"),
            context=self.qual, detail=f"{name}.{what}"))

    def _check_store(self, node: ast.AST, target: ast.expr):
        # x.attr = ... / x.attr[k] = ... with x tainted
        inner = target
        if isinstance(inner, ast.Subscript):
            inner = inner.value
        if (isinstance(inner, ast.Attribute)
                and isinstance(inner.value, ast.Name)
                and inner.value.id in self.tainted):
            what = (inner.attr if inner is target
                    else f"{inner.attr}[...]")
            self._flag(node, inner.value.id, what)

    def visit_Assign(self, node: ast.Assign):
        self.visit(node.value)
        for target in node.targets:
            self._check_store(node, target)
            for name in _target_names(target):
                if _read_call(node.value):
                    self.tainted[name] = node.lineno
                else:
                    self.tainted.pop(name, None)

    def visit_AugAssign(self, node: ast.AugAssign):
        self.visit(node.value)
        self._check_store(node, node.target)
        for name in _target_names(node.target):
            self.tainted.pop(name, None)

    def visit_For(self, node: ast.For):
        names = _target_names(node.target)
        if _read_call(node.iter):
            for name in names:
                self.tainted[name] = node.lineno
        else:
            for name in names:
                self.tainted.pop(name, None)
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    def visit_FunctionDef(self, node):
        pass  # closures get their own pass

    visit_AsyncFunctionDef = visit_FunctionDef


@rule("shared-struct-mutation",
      "objects read from the state store must be copied before mutation")
def check_shared_struct(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for mod in ctx.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            qual = f"{mod.rel}:{mod.enclosing_function(node)}"
            visitor = _TaintVisitor(mod, qual)
            for stmt in node.body:
                visitor.visit(stmt)
            findings.extend(visitor.findings)
    return findings
