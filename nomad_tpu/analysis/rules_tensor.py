"""nomadjit static prong: tensor-layer determinism / launch-discipline rules.

The solver tier's correctness contract is threefold — cross-mesh
bit-exactness, zero warm-path retraces, one host sync per launch — and
each clause has a statically detectable violation shape:

- ``reassociable-reduction-feeds-selection``: a float ``.sum()`` /
  ``jnp.sum`` / ``lax.psum`` whose result flows into ``argmax`` / a
  comparison / a ``where``/``select`` predicate inside a jitted graph
  (or a helper it calls).  XLA re-associates plain reductions per fusion
  context, so the same contributions summed in two compiled graphs
  (single-device vs mesh-sharded) can differ in the last ulp — enough to
  flip a near-tied portfolio selection (the PR 14 determinism bug, fixed
  by routing through the fixed-tree ``_pairwise_sum_xp``).  Integer
  reductions are associative and stay legal when the int dtype is
  visible (``dtype=jnp.int32`` / ``.astype(jnp.int32)``).
- ``host-sync-in-launch``: launch drivers (solver.py / placer.py) own
  the "ONE host sync per launch" contract: duplicated
  ``jax.device_get`` sites for the same launch, ``.item()``-style syncs
  in launch functions, and ``np.asarray(<jitted call>)`` readbacks
  (implicit device->host transfers the CPU-backend transfer guard
  cannot see — host and device share memory there) are all flagged.
- ``retrace-hazard``: Python ``for range()`` bounds, slice bounds, or
  shape-constructor arguments derived from traced (non-static) args of
  a jitted function — each new value re-traces; the static complement
  to ``jit_guard.no_retrace``.
- ``unguarded-launch``: a call to a jit-compiled kernel from solver.py /
  placer.py outside any ``no_retrace`` / ``_launch_guard`` /
  ``_warm_launch`` window, and a bare ``jax.device_put`` (no sharding)
  in a mesh-aware function outside a mesh-conditional branch (a bare
  put hands the sharded jit uncommitted arrays — the committed-vs-bare
  cache fork).
- ``prng-key-reuse``: one ``PRNGKey`` consumed by two sampling calls
  without ``split``/``fold_in``, or a loop-invariant key constructed
  inside a loop — every restart slot / auction round would replay the
  same stream.

Scope: tensor/ inside the package (host-sync/unguarded-launch further
restrict to solver.py/placer.py); everywhere in standalone fixture
trees.  Suppress deliberate exceptions in-line with ``# san-ok:
<reason>`` — findings are otherwise fixed in code, never baselined
(ANALYSIS.md "nomadjit").
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import AnalysisContext, Finding, Module, in_scope, rule
from .rules_concurrency import _suppressed
from .rules_jax import (_jit_decoration, _jitted_functions, _param_names,
                        _traced_uses)

SCOPE = ("tensor",)
LAUNCH_FILES = ("solver.py", "placer.py")

# wrappers whose function-name arguments run as device code
JIT_WRAPPERS = {"jit", "shard_map", "pmap"}
# context-manager factories that establish a guarded launch window
GUARD_NAMES = {"no_retrace", "_launch_guard", "_warm_launch"}
# helpers implementing a fixed-association reduction tree: calls to (or
# through) these are the blessed way to reduce floats feeding selection
PAIRWISE_TOKEN = "pairwise"
SELECTORS = {"argmax", "argmin", "top_k"}
PREDICATED = {"where", "select"}        # only args[0] (the predicate) selects
NUMPY_ALIASES = {"np", "numpy", "onp"}
SHAPE_FNS = {"zeros", "ones", "full", "empty", "arange", "eye",
             "linspace", "broadcast_to", "tile", "reshape"}
SYNC_ATTRS = {"item", "tolist", "block_until_ready"}
KEY_CONSUMERS = {"uniform", "normal", "randint", "permutation",
                 "bernoulli", "choice", "gumbel", "categorical",
                 "truncated_normal", "shuffle", "bits", "exponential"}
KEY_DERIVERS = {"split", "fold_in"}


# --- shared AST helpers -------------------------------------------------

def _final_name(func: ast.expr) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _int_dtype_token(node: ast.expr) -> bool:
    """Does this expression name an integer/bool dtype (jnp.int32,
    np.uint8, "int32", int, bool)?"""
    tokens = ("int", "uint", "bool")
    if isinstance(node, ast.Attribute):
        return node.attr.startswith(tokens)
    if isinstance(node, ast.Name):
        return node.id.startswith(tokens)
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value.startswith(tokens)
    return False


def _has_int_evidence(node: ast.AST) -> bool:
    """True if the subtree pins an integer dtype: a ``dtype=<int>``
    keyword or an ``.astype(<int>)`` call anywhere inside."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.keyword) and sub.arg == "dtype" \
                and _int_dtype_token(sub.value):
            return True
        if (isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "astype" and sub.args
                and _int_dtype_token(sub.args[0])):
            return True
    return False


def _under_pairwise(parents: Dict[ast.AST, ast.AST], node: ast.AST) -> bool:
    """Is `node` inside a call to a fixed-tree pairwise reducer?"""
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.Call):
            name = _final_name(cur.func)
            if name and PAIRWISE_TOKEN in name:
                return True
        cur = parents.get(cur)
    return False


def _fn_parents(fn: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(fn):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


MODULE_ALIASES = NUMPY_ALIASES | {"jnp", "xp"}


def _is_reduction(call: ast.Call) -> Optional[bool]:
    """Reassociable float reduction?  Returns True for a FULL (to
    scalar / collective) reduction, False for an axis reduction, None
    for not-a-reduction."""
    func = call.func
    name = _final_name(func)
    if name == "psum":
        return True
    if name != "sum" or not isinstance(func, ast.Attribute):
        return None
    has_axis = any(kw.arg == "axis" for kw in call.keywords)
    if _final_name(func.value) in MODULE_ALIASES:
        # module form jnp.sum(x[, axis]) — args[0] is the operand
        has_axis = has_axis or len(call.args) > 1
    else:
        # method form x.sum([axis]) — any positional arg is the axis
        has_axis = has_axis or bool(call.args)
    return not has_axis


def _device_functions(mod: Module) -> Dict[ast.FunctionDef,
                                           Optional[Tuple[str, ...]]]:
    """Functions that run as device code: jit-decorated/assigned defs
    (with their static argnames), defs handed to jit/shard_map/pmap by
    name, and — transitively, intra-module — defs they call by name.
    Pairwise reducers are excluded (their internals ARE the fix)."""
    by_name: Dict[str, ast.FunctionDef] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.FunctionDef):
            by_name.setdefault(node.name, node)
    device: Dict[ast.FunctionDef, Optional[Tuple[str, ...]]] = {}
    for fn, statics in _jitted_functions(mod).items():
        device[fn] = statics
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        if _final_name(node.func) not in JIT_WRAPPERS:
            continue
        for arg in node.args:
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Name) and sub.id in by_name:
                    device.setdefault(by_name[sub.id], None)
    # intra-module closure over by-name calls
    work = list(device)
    while work:
        fn = work.pop()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                callee = by_name.get(node.func.id)
                if callee is not None and callee not in device:
                    device[callee] = None
                    work.append(callee)
    return {fn: st for fn, st in device.items()
            if PAIRWISE_TOKEN not in fn.name}


def _jitted_global_names(ctx: AnalysisContext) -> Set[str]:
    """Names bound to jit-compiled callables anywhere in the analyzed
    tree: decorated defs plus ``f = jax.jit(impl)`` assignment targets."""
    names: Set[str] = set()
    for mod in ctx.modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.FunctionDef):
                if any(_jit_decoration(d) is not None
                       for d in node.decorator_list):
                    names.add(node.name)
            elif isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call):
                call = node.value
                jitted = _jit_decoration(call.func) is not None \
                    if isinstance(call.func, ast.Call) \
                    else _final_name(call.func) == "jit"
                if jitted:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            names.add(tgt.id)
    return names


# --- rule 1: reassociable-reduction-feeds-selection ---------------------

def _helper_sources(mod: Module,
                    device: Dict[ast.FunctionDef, object]) -> Set[str]:
    """Device helpers whose RETURN expression contains a raw (full,
    non-int, non-pairwise-routed) reduction — calls to them carry the
    reassociation hazard into the caller (the pre-PR-14
    ``_packing_score_xp`` shape)."""
    out: Set[str] = set()
    for fn in device:
        parents = _fn_parents(fn)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Return) or node.value is None:
                continue
            for sub in ast.walk(node.value):
                if not isinstance(sub, ast.Call):
                    continue
                if _is_reduction(sub) is not True:
                    continue
                if _has_int_evidence(sub) or _suppressed(mod, sub.lineno):
                    continue
                if _under_pairwise(parents, sub):
                    continue
                out.add(fn.name)
    return out


def _taint_names(fn: ast.FunctionDef, parents: Dict[ast.AST, ast.AST],
                 seeds: List[ast.AST], helper_names: Set[str]) -> Set[str]:
    """Names transitively assigned from the seed expressions (or calls
    to hazard helpers), with pairwise-reducer calls acting as cleansing
    boundaries."""
    seed_ids = {id(s) for s in seeds}

    def rhs_tainted(expr: ast.expr, tainted: Set[str]) -> bool:
        for sub in ast.walk(expr):
            if id(sub) in seed_ids and not _under_pairwise(parents, sub):
                return True
            if (isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name)
                    and sub.func.id in helper_names
                    and not _under_pairwise(parents, sub)):
                return True
            if (isinstance(sub, ast.Name) and sub.id in tainted
                    and isinstance(sub.ctx, ast.Load)
                    and not _under_pairwise(parents, sub)):
                return True
        return False

    tainted: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                value, targets = node.value, node.targets
            elif isinstance(node, ast.AugAssign):
                value, targets = node.value, [node.target]
            else:
                continue
            if not rhs_tainted(value, tainted):
                continue
            for tgt in targets:
                for sub in ast.walk(tgt):
                    if isinstance(sub, ast.Name) and sub.id not in tainted:
                        tainted.add(sub.id)
                        changed = True
    return tainted


def _selection_sink(fn: ast.FunctionDef, parents: Dict[ast.AST, ast.AST],
                    seeds: List[ast.AST], tainted: Set[str],
                    helper_names: Set[str],
                    direct_only: bool) -> Optional[str]:
    """First selection construct the taint reaches, or None.  With
    direct_only (axis reductions), only the seed expression itself or
    its directly-assigned name sitting immediately under the sink
    counts — elementwise axis sums feeding ordinary capacity arithmetic
    are not portfolio selections."""
    seed_ids = {id(s) for s in seeds}
    direct_names: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and id(node.value) in seed_ids:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    direct_names.add(tgt.id)

    def hits(expr: ast.expr, immediate: bool) -> bool:
        if direct_only:
            if id(expr) in seed_ids:
                return True
            if isinstance(expr, ast.Name) and expr.id in direct_names:
                return True
            if immediate:
                return False
            return False
        for sub in ast.walk(expr):
            if id(sub) in seed_ids and not _under_pairwise(parents, sub):
                return True
            if (isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name)
                    and sub.func.id in helper_names):
                return True
            if (isinstance(sub, ast.Name) and sub.id in tainted
                    and isinstance(sub.ctx, ast.Load)
                    and not _under_pairwise(parents, sub)):
                return True
        return False

    for node in ast.walk(fn):
        if isinstance(node, ast.Compare):
            for operand in [node.left] + list(node.comparators):
                if hits(operand, immediate=True):
                    return f"comparison at line {node.lineno}"
        elif isinstance(node, ast.Call):
            name = _final_name(node.func)
            if name in SELECTORS and node.args:
                for arg in node.args:
                    if hits(arg, immediate=True):
                        return f"{name}() at line {node.lineno}"
            elif name in PREDICATED and node.args:
                if hits(node.args[0], immediate=True):
                    return f"{name}() predicate at line {node.lineno}"
    return None


@rule("reassociable-reduction-feeds-selection",
      "float sum/psum results must not feed argmax/comparison/selection "
      "inside jitted graphs — route through _pairwise_sum_xp")
def check_reassoc_reduction(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for mod in ctx.modules:
        if not in_scope(mod.rel, SCOPE):
            continue
        device = _device_functions(mod)
        if not device:
            continue
        helper_names = _helper_sources(mod, device)
        for fn in device:
            parents = _fn_parents(fn)
            ordinal = 0
            sources: List[Tuple[ast.AST, bool, str]] = []
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    full = _is_reduction(node)
                    if full is None:
                        continue
                    if _has_int_evidence(node):
                        continue
                    parent = parents.get(node)
                    if (isinstance(parent, ast.Attribute)
                            and parent.attr == "astype"):
                        gp = parents.get(parent)
                        if isinstance(gp, ast.Call) and gp.args \
                                and _int_dtype_token(gp.args[0]):
                            continue       # sum(...).astype(int32)
                    if _under_pairwise(parents, node):
                        continue
                    if _suppressed(mod, node.lineno):
                        continue
                    token = _final_name(node.func) or "sum"
                    sources.append((node, bool(full), token))
            # calls to hazard helpers are full-reduction sources too
            for node in ast.walk(fn):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id in helper_names
                        and not _suppressed(mod, node.lineno)):
                    sources.append((node, True, node.func.id))
            for src, full, token in sources:
                tainted = _taint_names(fn, parents, [src],
                                       helper_names if full else set()) \
                    if full else set()
                sink = _selection_sink(fn, parents, [src], tainted,
                                       helper_names if full else set(),
                                       direct_only=not full)
                if sink is None:
                    continue
                ordinal += 1
                findings.append(Finding(
                    rule="reassociable-reduction-feeds-selection",
                    path=mod.rel, line=src.lineno, severity="error",
                    message=(f"reassociable float reduction '{token}' flows "
                             f"into {sink} inside device code '{fn.name}' — "
                             "XLA may re-associate it per fusion context and "
                             "flip a near-tied selection; route through "
                             "_pairwise_sum_xp (or pin an integer dtype)"),
                    context=f"{mod.rel}:{fn.name}",
                    detail=f"{token}#{ordinal}"))
    return findings


# --- rule 2: host-sync-in-launch ----------------------------------------

def _launch_scope(mod: Module) -> bool:
    from pathlib import Path

    parts = Path(mod.rel).parts
    if "nomad_tpu" not in parts:
        return True
    return in_scope(mod.rel, SCOPE) and Path(mod.rel).name in LAUNCH_FILES


@rule("host-sync-in-launch",
      "launch drivers get ONE explicit host sync per launch: no "
      "duplicated device_get sites, no .item()/np.asarray readbacks")
def check_host_sync_in_launch(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    jitted_names = _jitted_global_names(ctx)
    for mod in ctx.modules:
        if not _launch_scope(mod):
            continue
        jitted_here = set(_jitted_functions(mod))
        for fn in [n for n in ast.walk(mod.tree)
                   if isinstance(n, ast.FunctionDef)
                   and n not in jitted_here]:
            qual = f"{mod.rel}:{fn.name}"
            ordinal = 0

            def add(node, message, detail):
                findings.append(Finding(
                    rule="host-sync-in-launch", path=mod.rel,
                    line=node.lineno, severity="error", message=message,
                    context=qual, detail=detail))

            gets: Dict[str, List[ast.Call]] = {}
            is_launch_fn = False
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = _final_name(node.func)
                if isinstance(node.func, ast.Name) \
                        and node.func.id in jitted_names:
                    is_launch_fn = True
                if name == "device_get":
                    is_launch_fn = True
                    inner = ""
                    if node.args and isinstance(node.args[0], ast.Call):
                        inner = _final_name(node.args[0].func) or ""
                    gets.setdefault(inner, []).append(node)
                elif name == "asarray" and isinstance(
                        node.func, ast.Attribute) \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id in NUMPY_ALIASES \
                        and node.args and isinstance(node.args[0], ast.Call) \
                        and isinstance(node.args[0].func, ast.Name) \
                        and node.args[0].func.id in jitted_names:
                    if not _suppressed(mod, node.lineno):
                        ordinal += 1
                        add(node,
                            f"np.asarray({node.args[0].func.id}(...)) reads "
                            "the launch back through an IMPLICIT "
                            "device->host transfer (invisible to the "
                            "transfer guard on CPU backends) — use the "
                            "sanctioned jax.device_get",
                            f"asarray:{node.args[0].func.id}")
            for inner, sites in gets.items():
                if inner and len(sites) > 1:
                    for node in sites[1:]:
                        if _suppressed(mod, node.lineno):
                            continue
                        add(node,
                            f"duplicated jax.device_get({inner}(...)) call "
                            f"site in '{fn.name}' — a launch window gets "
                            "ONE host sync; collapse the branches into one "
                            "guarded call site",
                            f"dup-get:{inner}")
            if is_launch_fn:
                for node in ast.walk(fn):
                    if (isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Attribute)
                            and node.func.attr in SYNC_ATTRS
                            and not _suppressed(mod, node.lineno)):
                        add(node,
                            f".{node.func.attr}() inside launch driver "
                            f"'{fn.name}' is an extra host sync beyond the "
                            "launch's single device_get",
                            f".{node.func.attr}")
    return findings


# --- rule 3: retrace-hazard ---------------------------------------------

@rule("retrace-hazard",
      "no traced-value loop bounds, slice bounds, or shape arguments in "
      "jitted functions — each new value re-traces (static complement "
      "to jit_guard.no_retrace)")
def check_retrace_hazard(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for mod in ctx.modules:
        if not in_scope(mod.rel, SCOPE):
            continue
        for fn, statics in _jitted_functions(mod).items():
            traced = _param_names(fn) - set(statics)
            qual = f"{mod.rel}:{fn.name}"

            def add(node, message, detail):
                if not _suppressed(mod, node.lineno):
                    findings.append(Finding(
                        rule="retrace-hazard", path=mod.rel,
                        line=node.lineno, severity="error", message=message,
                        context=qual, detail=detail))

            for node in ast.walk(fn):
                if isinstance(node, ast.For) and isinstance(
                        node.iter, ast.Call) \
                        and _final_name(node.iter.func) == "range":
                    for use in _traced_uses(node.iter, traced):
                        add(node, f"`for range()` bound uses traced arg "
                            f"'{use.id}' in @jax.jit '{fn.name}' — the "
                            "loop unrolls per VALUE, re-tracing each time "
                            "(use lax.fori_loop or static_argnames)",
                            f"for-range:{use.id}")
                elif isinstance(node, ast.Subscript):
                    slices = [node.slice]
                    if isinstance(node.slice, ast.Tuple):
                        slices = list(node.slice.elts)
                    for sl in slices:
                        if not isinstance(sl, ast.Slice):
                            continue
                        for bound in (sl.lower, sl.upper, sl.step):
                            if bound is None:
                                continue
                            for use in _traced_uses(bound, traced):
                                add(node, f"slice bound uses traced arg "
                                    f"'{use.id}' in @jax.jit '{fn.name}' — "
                                    "slice sizes must be static (use "
                                    "lax.dynamic_slice for traced offsets)",
                                    f"slice:{use.id}")
                elif isinstance(node, ast.Call) and \
                        _final_name(node.func) in SHAPE_FNS:
                    for arg in node.args:
                        for use in _traced_uses(arg, traced):
                            add(node, f"shape argument of "
                                f"{_final_name(node.func)}() uses traced "
                                f"arg '{use.id}' in @jax.jit '{fn.name}' — "
                                "shapes derived from traced VALUES "
                                "re-trace per value",
                                f"shape:{use.id}")
    return findings


# --- rule 4: unguarded-launch -------------------------------------------

def _guarded(parents: Dict[ast.AST, ast.AST], node: ast.AST) -> bool:
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.With):
            for item in cur.items:
                for sub in ast.walk(item.context_expr):
                    if isinstance(sub, ast.Call) and \
                            _final_name(sub.func) in GUARD_NAMES:
                        return True
        cur = parents.get(cur)
    return False


@rule("unguarded-launch",
      "solver/placer jit launches run under a shape-keyed no_retrace "
      "window; mesh-aware device_puts carry an explicit NamedSharding")
def check_unguarded_launch(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    jitted_names = _jitted_global_names(ctx)
    for mod in ctx.modules:
        if not _launch_scope(mod):
            continue
        jitted_here = set(_jitted_functions(mod))
        for fn in [n for n in ast.walk(mod.tree)
                   if isinstance(n, ast.FunctionDef)
                   and n not in jitted_here]:
            parents = _fn_parents(fn)
            qual = f"{mod.rel}:{fn.name}"
            mentions_mesh = any(isinstance(n, ast.Name) and n.id == "mesh"
                                for n in ast.walk(fn)) \
                or "mesh" in _param_names(fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = _final_name(node.func)
                if isinstance(node.func, ast.Name) \
                        and node.func.id in jitted_names:
                    if not _guarded(parents, node) \
                            and not _suppressed(mod, node.lineno):
                        findings.append(Finding(
                            rule="unguarded-launch", path=mod.rel,
                            line=node.lineno, severity="error",
                            message=(f"jit launch {node.func.id}(...) in "
                                     f"'{fn.name}' runs outside a "
                                     "shape-keyed no_retrace window — warm "
                                     "retraces and implicit transfers go "
                                     "undetected (wrap in _warm_launch / "
                                     "_launch_guard)"),
                            context=qual, detail=f"launch:{node.func.id}"))
                elif name == "device_put" and len(node.args) == 1 \
                        and not node.keywords and mentions_mesh:
                    branch_ok = False
                    cur = parents.get(node)
                    while cur is not None:
                        if isinstance(cur, ast.If) and any(
                                isinstance(s, ast.Name) and s.id == "mesh"
                                for s in ast.walk(cur.test)):
                            branch_ok = True
                            break
                        cur = parents.get(cur)
                    if not branch_ok and not _suppressed(mod, node.lineno):
                        findings.append(Finding(
                            rule="unguarded-launch", path=mod.rel,
                            line=node.lineno, severity="error",
                            message=(f"bare jax.device_put in mesh-aware "
                                     f"'{fn.name}' — without an explicit "
                                     "NamedSharding the sharded jit sees "
                                     "uncommitted single-device arrays "
                                     "(committed-vs-bare cache fork)"),
                            context=qual, detail="bare-device_put"))
    return findings


# --- rule 5: prng-key-reuse ---------------------------------------------

def _is_key_ctor(call: ast.Call) -> bool:
    return _final_name(call.func) in ("PRNGKey", "key")


@rule("prng-key-reuse",
      "a PRNGKey feeds ONE sampling call — reuse without fold_in/split "
      "replays the same stream across restarts/rounds")
def check_prng_key_reuse(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for mod in ctx.modules:
        if not in_scope(mod.rel, SCOPE):
            continue
        fns = [n for n in ast.walk(mod.tree)
               if isinstance(n, (ast.FunctionDef, ast.Lambda))]
        for fn in fns:
            nested = [n for n in ast.walk(fn)
                      if isinstance(n, (ast.FunctionDef, ast.Lambda))
                      and n is not fn]
            nested_nodes = {id(x) for sub in nested for x in ast.walk(sub)}
            parents = _fn_parents(fn)
            qual = (f"{mod.rel}:{getattr(fn, 'name', '<lambda>')}")

            # (a) a named key consumed by 2+ sampling calls
            key_names: Set[str] = set()
            for node in ast.walk(fn):
                if id(node) in nested_nodes:
                    continue
                if isinstance(node, ast.Assign) and isinstance(
                        node.value, ast.Call) and _is_key_ctor(node.value):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            key_names.add(tgt.id)
            uses: Dict[str, List[ast.Call]] = {k: [] for k in key_names}
            for node in ast.walk(fn):
                if id(node) in nested_nodes or not isinstance(node, ast.Call):
                    continue
                if _final_name(node.func) not in KEY_CONSUMERS:
                    continue
                for arg in node.args:
                    if isinstance(arg, ast.Name) and arg.id in key_names:
                        uses[arg.id].append(node)
            for key, sites in uses.items():
                for node in sites[1:]:
                    if _suppressed(mod, node.lineno):
                        continue
                    findings.append(Finding(
                        rule="prng-key-reuse", path=mod.rel,
                        line=node.lineno, severity="error",
                        message=(f"PRNGKey '{key}' consumed again by "
                                 f"{_final_name(node.func)}() — identical "
                                 "stream both times; derive per-use keys "
                                 "with jax.random.split/fold_in"),
                        context=qual, detail=f"reuse:{key}"))

            # (b) a loop-invariant key constructed inside the loop body
            for node in ast.walk(fn):
                if id(node) in nested_nodes or not isinstance(node, ast.Call):
                    continue
                if not _is_key_ctor(node):
                    continue
                loop_targets: Set[str] = set()
                in_loop = False
                cur = parents.get(node)
                derived = False
                while cur is not None:
                    if isinstance(cur, ast.Call) and \
                            _final_name(cur.func) in KEY_DERIVERS:
                        derived = True
                    if isinstance(cur, (ast.FunctionDef, ast.Lambda)) \
                            and cur is not fn:
                        # a nested fn's key depends on ITS params
                        derived = True
                    if isinstance(cur, ast.For):
                        in_loop = True
                        for sub in ast.walk(cur.target):
                            if isinstance(sub, ast.Name):
                                loop_targets.add(sub.id)
                    elif isinstance(cur, ast.While):
                        in_loop = True
                    cur = parents.get(cur)
                if not in_loop or derived:
                    continue
                seed_names = {s.id for arg in node.args
                              for s in ast.walk(arg)
                              if isinstance(s, ast.Name)}
                if seed_names & loop_targets:
                    continue
                if _suppressed(mod, node.lineno):
                    continue
                findings.append(Finding(
                    rule="prng-key-reuse", path=mod.rel,
                    line=node.lineno, severity="error",
                    message=("loop-invariant PRNGKey constructed inside a "
                             f"loop in '{qual.split(':')[-1]}' — every "
                             "round replays the same stream; fold_in the "
                             "round index"),
                    context=qual, detail="loop-invariant-key"))
    return findings


# the nomadjit static prong, runnable alone via --tensor (ANALYSIS.md)
TENSOR_RULES = (
    "reassociable-reduction-feeds-selection",
    "host-sync-in-launch",
    "retrace-hazard",
    "unguarded-launch",
    "prng-key-reuse",
)
