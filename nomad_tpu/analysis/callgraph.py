"""Name-based call graph over the analyzed modules.

Resolution is deliberately over-approximate (soundness beats precision
for a determinism gate): `self.m(...)` resolves to every method named
`m` — same class first, then same module, then anywhere; a bare `f(...)`
resolves to the same-module function or any module-level function with
that name; `obj.m(...)` resolves to every analyzed function named `m`.
Dynamic dispatch (`getattr(store, op)` in the FSM) is handled by the
FSM rule rooting at the MUTATIONS name set instead of chasing the call.

Nested `def`s are not separate graph nodes: a function's edges and body
include its closures, so reaching the function reaches everything it
could possibly run.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Set

from .core import Module


@dataclass(frozen=True)
class FuncInfo:
    module_rel: str
    class_name: Optional[str]   # None for module-level functions
    name: str
    node: ast.AST = None

    def __hash__(self):
        return hash((self.module_rel, self.class_name, self.name))

    def __eq__(self, other):
        return (self.module_rel, self.class_name, self.name) == (
            other.module_rel, other.class_name, other.name)

    @property
    def qualname(self) -> str:
        if self.class_name:
            return f"{self.class_name}.{self.name}"
        return self.name


def _called_names(fn_node: ast.AST):
    """Yield ("self"|"name"|"attr", name) for every call in the subtree
    (closures included)."""
    for node in ast.walk(fn_node):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Name):
            yield "name", func.id
        elif isinstance(func, ast.Attribute):
            if isinstance(func.value, ast.Name) and func.value.id == "self":
                yield "self", func.attr
            else:
                yield "attr", func.attr


class CallGraph:
    def __init__(self, modules: List[Module]):
        self.functions: List[FuncInfo] = []
        self.by_name: Dict[str, List[FuncInfo]] = {}
        self._index(modules)
        self._edges: Dict[FuncInfo, Set[FuncInfo]] = {}

    def _add(self, info: FuncInfo) -> None:
        self.functions.append(info)
        self.by_name.setdefault(info.name, []).append(info)

    def _index(self, modules: List[Module]) -> None:
        for mod in modules:
            for stmt in mod.tree.body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._add(FuncInfo(mod.rel, None, stmt.name, stmt))
                elif isinstance(stmt, ast.ClassDef):
                    for sub in stmt.body:
                        if isinstance(sub, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)):
                            self._add(FuncInfo(mod.rel, stmt.name,
                                               sub.name, sub))

    def resolve(self, caller: FuncInfo, kind: str, name: str) -> List[FuncInfo]:
        candidates = self.by_name.get(name)
        if not candidates:
            return []
        if kind == "self":
            same_class = [c for c in candidates
                          if c.module_rel == caller.module_rel
                          and c.class_name == caller.class_name
                          and c.class_name is not None]
            if same_class:
                return same_class
            same_module = [c for c in candidates
                           if c.module_rel == caller.module_rel
                           and c.class_name is not None]
            if same_module:
                return same_module
            return [c for c in candidates if c.class_name is not None]
        if kind == "name":
            same_module = [c for c in candidates
                           if c.module_rel == caller.module_rel
                           and c.class_name is None]
            if same_module:
                return same_module
            return [c for c in candidates if c.class_name is None]
        return list(candidates)  # plain attribute call: any match

    def edges(self, fn: FuncInfo) -> Set[FuncInfo]:
        cached = self._edges.get(fn)
        if cached is not None:
            return cached
        out: Set[FuncInfo] = set()
        for kind, name in _called_names(fn.node):
            out.update(self.resolve(fn, kind, name))
        self._edges[fn] = out
        return out

    def reachable(self, roots: List[FuncInfo]) -> Set[FuncInfo]:
        seen: Set[FuncInfo] = set()
        frontier = list(roots)
        while frontier:
            fn = frontier.pop()
            if fn in seen:
                continue
            seen.add(fn)
            frontier.extend(self.edges(fn) - seen)
        return seen
