"""Analyzer driver: module loading, findings, rule registry, baseline.

The analyzer is pure-AST (no imports of the code under analysis, no jax
dependency) so it runs in milliseconds as a pre-test gate. Each rule is
a callable taking an :class:`AnalysisContext` and returning findings.

Baselining: findings are keyed by (rule, file, context, detail) — NOT by
line number — so unrelated edits that shift lines don't invalidate the
baseline, while new instances of a violation in the same function do
show up (distinct detail ordinals).
"""

from __future__ import annotations

import ast
import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Tuple

SEVERITIES = ("error", "warning")


@dataclass
class Finding:
    rule: str
    path: str        # posix path relative to the analysis root
    line: int
    severity: str
    message: str
    context: str     # enclosing function qualname or "<module>"
    detail: str      # stable token used (with rule/path/context) as baseline key

    @property
    def key(self) -> Tuple[str, str, str, str]:
        return (self.rule, self.path, self.context, self.detail)

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.rule}] {self.severity}: "
                f"{self.message} (in {self.context})")


@dataclass
class Module:
    path: Path
    rel: str                 # posix, relative to the analysis root
    tree: ast.Module
    source: str
    parents: Dict[ast.AST, ast.AST] = field(default_factory=dict)

    def build_parents(self) -> None:
        if self.parents:
            return
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node

    def enclosing_function(self, node: ast.AST) -> str:
        """Qualname of the innermost def/class chain containing `node`."""
        self.build_parents()
        names: List[str] = []
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                names.append(cur.name)
            cur = self.parents.get(cur)
        return ".".join(reversed(names)) or "<module>"


class AnalysisContext:
    def __init__(self, modules: List[Module], root: Path):
        self.modules = modules
        self.root = root
        self._callgraph = None

    @property
    def callgraph(self):
        if self._callgraph is None:
            from .callgraph import CallGraph
            self._callgraph = CallGraph(self.modules)
        return self._callgraph


def in_scope(rel: str, subdirs: Tuple[str, ...]) -> bool:
    """Rule scoping: inside the nomad_tpu package, restrict to the given
    package subdirectories; outside it (fixture trees), apply everywhere
    so the rule is testable on standalone snippets."""
    parts = Path(rel).parts
    if "nomad_tpu" not in parts:
        return True
    i = parts.index("nomad_tpu")
    return len(parts) > i + 1 and parts[i + 1] in subdirs


# --- rule registry ---

RuleFn = Callable[[AnalysisContext], List[Finding]]
_RULES: Dict[str, Tuple[RuleFn, str]] = {}


def rule(rule_id: str, doc: str) -> Callable[[RuleFn], RuleFn]:
    def register(fn: RuleFn) -> RuleFn:
        _RULES[rule_id] = (fn, doc)
        return fn
    return register


def all_rules() -> Dict[str, Tuple[RuleFn, str]]:
    # importing the rule modules populates the registry
    from . import (rules_concurrency, rules_flow, rules_fsm,  # noqa: F401
                   rules_hygiene, rules_jax, rules_ownership, rules_tensor)
    return dict(_RULES)


# --- module loading ---

def iter_py_files(paths: Iterable[Path]) -> Iterator[Path]:
    for p in paths:
        if p.is_file() and p.suffix == ".py":
            yield p
        elif p.is_dir():
            yield from sorted(p.rglob("*.py"))


def load_modules(paths: Iterable[Path], root: Path) -> List[Module]:
    modules = []
    for f in iter_py_files(paths):
        src = f.read_text()
        try:
            tree = ast.parse(src, filename=str(f))
        except SyntaxError:
            continue  # not our concern; ruff/pytest report it
        try:
            rel = f.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = f.as_posix()
        modules.append(Module(path=f, rel=rel, tree=tree, source=src))
    return modules


def run_analysis(paths: Optional[Iterable[Path]] = None,
                 rules: Optional[Iterable[str]] = None,
                 root: Optional[Path] = None) -> List[Finding]:
    """Run the given rules (default: all) over the given paths (default:
    the nomad_tpu package) and return findings sorted by location."""
    pkg_dir = Path(__file__).resolve().parent.parent
    if paths is None:
        paths = [pkg_dir]
    paths = [Path(p) for p in paths]
    if root is None:
        root = pkg_dir.parent
    ctx = AnalysisContext(load_modules(paths, root), root)
    registry = all_rules()
    wanted = set(rules) if rules is not None else set(registry)
    unknown = wanted - set(registry)
    if unknown:
        raise ValueError(f"unknown rule(s): {sorted(unknown)}")
    findings: List[Finding] = []
    for rule_id in sorted(wanted):
        findings.extend(registry[rule_id][0](ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


# --- baseline ---

def baseline_path() -> Path:
    return Path(__file__).resolve().parent / "baseline.json"


def load_baseline(path: Optional[Path] = None) -> set:
    path = path or baseline_path()
    if not path.exists():
        return set()
    data = json.loads(path.read_text())
    return {(e["rule"], e["file"], e["context"], e["detail"])
            for e in data.get("findings", [])}


def write_baseline(findings: List[Finding], path: Optional[Path] = None) -> Path:
    path = path or baseline_path()
    entries = sorted({f.key for f in findings})
    data = {
        "comment": ("Allowlisted pre-existing findings; the gate is "
                    "zero NEW violations. Regenerate with "
                    "`python -m nomad_tpu.analysis --write-baseline` "
                    "only after triaging each addition (see ANALYSIS.md)."),
        "findings": [{"rule": r, "file": f, "context": c, "detail": d}
                     for r, f, c, d in entries],
    }
    path.write_text(json.dumps(data, indent=2) + "\n")
    return path


def partition(findings: List[Finding],
              baseline: set) -> Tuple[List[Finding], set]:
    """Split into (new findings, stale baseline keys)."""
    new = [f for f in findings if f.key not in baseline]
    stale = baseline - {f.key for f in findings}
    return new, stale
