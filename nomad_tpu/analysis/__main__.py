"""CLI: `python -m nomad_tpu.analysis [paths...]`.

Exit status is non-zero iff any finding is not in the baseline — the
shape CI wants: pre-existing debt is allowlisted, new violations fail.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .core import (all_rules, baseline_path, load_baseline, partition,
                   run_analysis, write_baseline)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m nomad_tpu.analysis",
        description="AST invariant checker (see ANALYSIS.md)")
    parser.add_argument("paths", nargs="*", type=Path,
                        help="files/dirs to analyze (default: the "
                             "nomad_tpu package)")
    parser.add_argument("--rule", action="append", dest="rules",
                        metavar="ID", help="run only this rule "
                        "(repeatable); default: all")
    parser.add_argument("--baseline", type=Path, default=None,
                        help=f"baseline file (default {baseline_path()})")
    parser.add_argument("--no-baseline", action="store_true",
                        help="fail on every finding, allowlist ignored")
    parser.add_argument("--write-baseline", action="store_true",
                        help="rewrite the baseline from current findings")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable output")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--threads", action="store_true",
                        help="dump every discovered thread entrypoint "
                             "(Thread/Timer/executor-submit site) and exit")
    parser.add_argument("--root", type=Path, default=None,
                        help="root for relative paths (default: repo root)")
    parser.add_argument("--ownership", action="store_true",
                        help="run only the nomadown ownership/aliasing "
                             "rules (see ANALYSIS.md)")
    parser.add_argument("--tensor", action="store_true",
                        help="run only the nomadjit tensor determinism/"
                             "launch-discipline rules (see ANALYSIS.md)")
    parser.add_argument("--flow", action="store_true",
                        help="run only the nomadflow mutation→event "
                             "completeness rules (see ANALYSIS.md)")
    parser.add_argument("--modelcheck", action="store_true",
                        help="run the deterministic interleaving model "
                             "checker (nomadcheck dynamic prong) and exit")
    parser.add_argument("--seeds", type=int, default=3, metavar="N",
                        help="schedules per scenario per policy for "
                             "--modelcheck (default 3); base seed comes "
                             "from NOMAD_TPU_CHECK_SEED")
    args = parser.parse_args(argv)

    if args.ownership:
        from .rules_ownership import OWNERSHIP_RULES
        args.rules = list(OWNERSHIP_RULES)

    if args.tensor:
        from .rules_tensor import TENSOR_RULES
        args.rules = (args.rules or []) + list(TENSOR_RULES)

    if args.flow:
        from .rules_flow import FLOW_RULES
        args.rules = (args.rules or []) + list(FLOW_RULES)

    if args.modelcheck:
        from .modelcheck import seed_from_env, smoke
        base = seed_from_env()
        print(f"nomadcheck: base seed {base} "
              f"(replay with NOMAD_TPU_CHECK_SEED={base}), "
              f"{args.seeds} seed(s)/scenario/policy")
        failures = smoke(base, seeds_per_scenario=args.seeds)
        print(f"nomadcheck: {failures} failing schedule(s)")
        return 1 if failures else 0

    if args.list_rules:
        for rule_id, (_, doc) in sorted(all_rules().items()):
            print(f"{rule_id}: {doc}")
        return 0

    if args.threads:
        from .core import load_modules
        from .rules_concurrency import discover_thread_sites
        pkg_dir = Path(__file__).resolve().parent.parent
        paths = args.paths or [pkg_dir]
        root = args.root or pkg_dir.parent
        sites = discover_thread_sites(load_modules(paths, root))
        for s in sites:
            print(f"{s.module_rel}:{s.lineno}: {s.factory} -> {s.target}")
        print(f"{len(sites)} thread entrypoint site(s)")
        return 0

    findings = run_analysis(paths=args.paths or None, rules=args.rules,
                            root=args.root)

    if args.write_baseline:
        path = write_baseline(findings, args.baseline)
        print(f"wrote {len(findings)} finding(s) to {path}")
        return 0

    baseline = set() if args.no_baseline else load_baseline(args.baseline)
    new, stale = partition(findings, baseline)

    if args.as_json:
        print(json.dumps({
            "findings": [vars(f) for f in findings],
            "new": [vars(f) for f in new],
            "stale_baseline": sorted(stale),
        }, indent=2))
        return 1 if new else 0

    for f in new:
        print(f.render())
    baselined = len(findings) - len(new)
    print(f"{len(findings)} finding(s): {len(new)} new, "
          f"{baselined} baselined"
          + (f", {len(stale)} stale baseline entrie(s) — "
             "consider --write-baseline" if stale else ""))
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
