"""nomadsan static prong: thread entrypoints, lock regions, two rules.

`shared-mutation-unlocked` — the control plane is ~25 threaded modules
whose objects are mutated from watcher loops, worker pools, timers and
the caller's thread. Per class, this rule discovers every thread
entrypoint (``threading.Thread(target=self.x)``, ``threading.Timer``,
executor ``.submit``, thread-spawned closures), adds the public-method
surface as one collective "api" root (public methods may be called from
any thread), computes which methods each root reaches via self-calls,
and flags any ``self.attr`` mutation site that (a) sits in a method
reachable from >= 2 distinct roots of a class that actually runs
threads and (b) holds no lock at the mutation site. Attributes bound to
thread-safe primitives in ``__init__`` (locks, events, queues, deques)
are exempt, as are ``__init__`` itself and methods following the
``*_locked`` suffix convention (their callers own the lock).

`lock-order-cycle` — the static generalization of PR 1's pairwise
``lock-order`` rule: build the package-wide lock-acquisition-order
graph (lock names qualified by class, so ``EvalBroker._lock`` and
``PlanQueue._lock`` are distinct nodes), including interprocedural
edges — a function holding L that calls ``g()`` points L at every lock
``g`` transitively acquires — and flag every cycle as a deadlock
candidate. Attribute-kind calls (``obj.m()``) are followed only when
the name resolves uniquely in the tree; anything noisier is the runtime
prong's job (sanitizer.py).

False positives are suppressed in code with a ``# san-ok: <why>``
comment on the flagged line (or the line above), never baselined — the
justification lives next to the code it excuses.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import CallGraph, FuncInfo
from .core import AnalysisContext, Finding, Module, rule

SUPPRESS_TOKEN = "san-ok:"

# attribute-call names that mutate the receiver container in place
MUTATORS = {
    "append", "appendleft", "add", "insert", "extend", "update",
    "pop", "popitem", "popleft", "remove", "discard", "clear",
    "setdefault", "sort", "reverse",
}

# constructors whose instances are internally synchronized: attributes
# bound to these in __init__ are not "shared mutable state"
THREADSAFE_CTORS = {
    "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
    "Event", "Barrier", "Queue", "LifoQueue", "PriorityQueue",
    "SimpleQueue", "deque", "local",
}

# plain-container constructors: a mutator-method call (`self.x.add(...)`)
# only counts as container mutation when __init__ binds the attribute to
# one of these (or a display literal). Anything else — e.g.
# `self.periodic = PeriodicDispatcher(...)` — is a delegated call to an
# object that owns its own locking and is analyzed on its own.
CONTAINER_CTORS = {
    "dict", "list", "set", "defaultdict", "OrderedDict", "Counter",
    "ChainMap",
}

LOCK_NAME_TOKENS = ("lock", "cond", "mutex", "sem")


def _analysis_scope(mod: Module) -> bool:
    """Everything in the package except the analyzer itself; fixture
    trees (outside nomad_tpu) are always in scope so rules are testable
    on standalone snippets."""
    from pathlib import Path

    parts = Path(mod.rel).parts
    if "nomad_tpu" not in parts:
        return True
    i = parts.index("nomad_tpu")
    return not (len(parts) > i + 1 and parts[i + 1] == "analysis")


def _suppressed(mod: Module, lineno: int) -> bool:
    lines = mod.source.splitlines()
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(lines) and SUPPRESS_TOKEN in lines[ln - 1]:
            return True
    return False


def _dotted_parts(node: ast.expr) -> Optional[List[str]]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None


def _lockish(name: str) -> bool:
    return any(tok in name.lower() for tok in LOCK_NAME_TOKENS)


def _qualified_lock_name(expr: ast.expr, class_name: Optional[str]) -> str:
    """Class-qualified dotted name of a lock-ish `with` context, or "".
    `self._lock` in class C -> "C._lock" (distinct graph nodes per
    class); bare/module locks keep their dotted spelling."""
    parts = _dotted_parts(expr)
    if not parts or not _lockish(parts[-1]):
        return ""
    if parts[0] == "self":
        parts = parts[1:]
        if class_name:
            parts = [class_name] + parts
    return ".".join(parts)


# --------------------------------------------------------------------
# thread-entrypoint discovery
# --------------------------------------------------------------------

@dataclass(frozen=True)
class ThreadSite:
    module_rel: str
    lineno: int
    factory: str                 # "Thread" | "Timer" | "submit"
    target: str                  # source-ish description of the callable


def _thread_target_expr(call: ast.Call) -> Optional[Tuple[str, ast.expr]]:
    """(factory, target-callable expr) for thread-spawning calls."""
    func = call.func
    name = func.attr if isinstance(func, ast.Attribute) else (
        func.id if isinstance(func, ast.Name) else "")
    if name == "Thread":
        for kw in call.keywords:
            if kw.arg == "target":
                return "Thread", kw.value
        return None
    if name == "Timer":
        # Timer(interval, function, ...)
        if len(call.args) >= 2:
            return "Timer", call.args[1]
        for kw in call.keywords:
            if kw.arg == "function":
                return "Timer", kw.value
        return None
    if name == "submit" and isinstance(func, ast.Attribute) and call.args:
        return "submit", call.args[0]
    return None


def discover_thread_sites(modules: List[Module]) -> List[ThreadSite]:
    """Every Thread/Timer/executor-submit spawn site in the tree (the
    pass `python -m nomad_tpu.analysis --threads` dumps)."""
    sites: List[ThreadSite] = []
    for mod in modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            hit = _thread_target_expr(node)
            if hit is None:
                continue
            factory, target = hit
            parts = _dotted_parts(target)
            desc = ".".join(parts) if parts else (
                "<lambda>" if isinstance(target, ast.Lambda) else
                ast.unparse(target) if hasattr(ast, "unparse") else "<expr>")
            sites.append(ThreadSite(mod.rel, node.lineno, factory, desc))
    return sites


# --------------------------------------------------------------------
# shared-mutation-unlocked
# --------------------------------------------------------------------

@dataclass
class _Mutation:
    attr: str
    kind: str        # "assign" | "subscript" | mutator method name
    lineno: int
    locked: bool     # any lock-named `with` encloses the site
    method: str      # owning method name (or "method.closure")


class _MethodScan(ast.NodeVisitor):
    """One pass over a method scope: self-call edges, self.attr
    mutations with held-lock context, thread spawns. Nested defs that
    are thread targets are excluded (they are their own root scope);
    other closures stay attributed to the enclosing method (they may
    run inline)."""

    def __init__(self, skip_defs: Set[ast.AST]):
        self.skip_defs = skip_defs
        self.self_calls: Set[str] = set()
        self.mutations: List[Tuple[str, str, int]] = []  # (attr, kind, line)
        self.locked_lines: List[Tuple[int, int]] = []    # with-lock spans
        self._lock_depth = 0
        self.mutation_ctx: List[Tuple[str, str, int, bool]] = []

    def visit_FunctionDef(self, node):
        if node in self.skip_defs:
            return
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_With(self, node: ast.With):
        lockish = any(
            _lockish((_dotted_parts(item.context_expr) or ["?"])[-1])
            for item in node.items
            if _dotted_parts(item.context_expr))
        for item in node.items:
            self.visit(item.context_expr)
        if lockish:
            self._lock_depth += 1
        for stmt in node.body:
            self.visit(stmt)
        if lockish:
            self._lock_depth -= 1

    def _self_attr(self, expr: ast.expr) -> Optional[str]:
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            return expr.attr
        return None

    def _note(self, attr: str, kind: str, lineno: int):
        self.mutation_ctx.append((attr, kind, lineno, self._lock_depth > 0))

    def _check_target(self, target: ast.expr, lineno: int):
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._check_target(elt, lineno)
            return
        attr = self._self_attr(target)
        if attr is not None:
            self._note(attr, "assign", lineno)
            return
        if isinstance(target, ast.Subscript):
            attr = self._self_attr(target.value)
            if attr is not None:
                self._note(attr, "subscript", lineno)

    def visit_Assign(self, node: ast.Assign):
        for target in node.targets:
            self._check_target(target, node.lineno)
        self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign):
        self._check_target(node.target, node.lineno)
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            self._check_target(node.target, node.lineno)
            self.visit(node.value)

    def visit_Delete(self, node: ast.Delete):
        for target in node.targets:
            if isinstance(target, ast.Subscript):
                attr = self._self_attr(target.value)
                if attr is not None:
                    self._note(attr, "subscript", node.lineno)
            elif (attr := self._self_attr(target)) is not None:
                self._note(attr, "assign", node.lineno)

    def visit_Call(self, node: ast.Call):
        func = node.func
        if isinstance(func, ast.Attribute):
            if func.attr in MUTATORS:
                attr = self._self_attr(func.value)
                if attr is not None:
                    self._note(attr, func.attr, node.lineno)
            if (isinstance(func.value, ast.Name)
                    and func.value.id == "self"):
                self.self_calls.add(func.attr)
        self.generic_visit(node)


def _init_attr_kinds(init_node: Optional[ast.AST]
                     ) -> Tuple[Set[str], Set[str], Set[str]]:
    """(threadsafe, container, other-call) attribute sets from __init__
    assignments. Attrs never assigned in __init__ land in none of them
    (treated as containers, over-approximately)."""
    safe: Set[str] = set()
    containers: Set[str] = set()
    delegates: Set[str] = set()
    if init_node is None:
        return safe, containers, delegates
    for node in ast.walk(init_node):
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        attrs = [t.attr for t in node.targets
                 if isinstance(t, ast.Attribute)
                 and isinstance(t.value, ast.Name) and t.value.id == "self"]
        if not attrs:
            continue
        if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                              ast.DictComp, ast.SetComp)):
            containers.update(attrs)
        elif isinstance(value, ast.Call):
            parts = _dotted_parts(value.func)
            ctor = parts[-1] if parts else ""
            if ctor in THREADSAFE_CTORS:
                safe.update(attrs)
            elif ctor in CONTAINER_CTORS:
                containers.update(attrs)
            else:
                delegates.update(attrs)
    return safe, containers, delegates


class _ClassModel:
    def __init__(self, mod: Module, node: ast.ClassDef):
        self.mod = mod
        self.node = node
        self.name = node.name
        self.methods: Dict[str, ast.AST] = {
            s.name: s for s in node.body
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))}
        (self.safe_attrs, self.container_attrs,
         self.delegate_attrs) = _init_attr_kinds(self.methods.get("__init__"))
        # thread-target closures: nested def nodes spawned as threads
        self.closure_roots: Dict[str, Tuple[str, ast.AST]] = {}
        # method-name entrypoints via self.<m> targets inside this class
        self.entry_methods: Set[str] = set()
        self._discover_spawns()
        self.scans: Dict[str, _MethodScan] = {}
        skip = {node for _, node in self.closure_roots.values()}
        for mname, mnode in self.methods.items():
            scan = _MethodScan(skip)
            for stmt in mnode.body:
                scan.visit(stmt)
            self.scans[mname] = scan
        for rname, (owner, cnode) in self.closure_roots.items():
            scan = _MethodScan(set())
            for stmt in cnode.body:
                scan.visit(stmt)
            self.scans[rname] = scan

    def _discover_spawns(self):
        for mname, mnode in self.methods.items():
            nested = {n.name: n for n in ast.walk(mnode)
                      if isinstance(n, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))
                      and n is not mnode}
            for node in ast.walk(mnode):
                if not isinstance(node, ast.Call):
                    continue
                hit = _thread_target_expr(node)
                if hit is None:
                    continue
                _, target = hit
                parts = _dotted_parts(target)
                if parts and parts[0] == "self" and len(parts) == 2:
                    if parts[1] in self.methods:
                        self.entry_methods.add(parts[1])
                elif (isinstance(target, ast.Name)
                      and target.id in nested):
                    root = f"{mname}.{target.id}"
                    self.closure_roots[root] = (mname, nested[target.id])

    def roots(self) -> Dict[str, Set[str]]:
        """root name -> set of scan keys (methods/closures) it reaches
        via self-calls."""
        out: Dict[str, Set[str]] = {}
        # a public method that IS a thread entrypoint (e.g. Worker.run)
        # is excluded from the collective api root: calling it directly
        # while it also runs as the thread is a usage error, not a race
        public = {m for m in self.methods
                  if not m.startswith("_") and m != "__init__"
                  and m not in self.entry_methods}

        def reach(seed: Set[str]) -> Set[str]:
            seen: Set[str] = set()
            frontier = [s for s in seed if s in self.scans]
            while frontier:
                cur = frontier.pop()
                if cur in seen:
                    continue
                seen.add(cur)
                for callee in self.scans[cur].self_calls:
                    if callee in self.scans and callee not in seen:
                        frontier.append(callee)
            return seen

        if public:
            out["api"] = reach(public)
        for m in self.entry_methods:
            out[f"thread:{m}"] = reach({m})
        for rname in self.closure_roots:
            seen = reach({rname})
            seen |= reach(self.scans[rname].self_calls)
            seen.add(rname)
            out[f"thread:{rname}"] = seen
        return out


@rule("shared-mutation-unlocked",
      "self.attr mutation reachable from >=2 thread roots with no lock "
      "held at the site")
def check_shared_mutation_unlocked(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    models: List[_ClassModel] = []
    # global pass: `target=obj.m` spawns outside the class mark every
    # class owning method m as threaded via that entrypoint
    attr_targets: Set[str] = set()
    modules = [m for m in ctx.modules if _analysis_scope(m)]
    for mod in modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                hit = _thread_target_expr(node)
                if hit is None:
                    continue
                parts = _dotted_parts(hit[1])
                if parts and parts[0] != "self" and len(parts) >= 2:
                    attr_targets.add(parts[-1])
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                models.append(_ClassModel(mod, node))
    for model in models:
        for mname in list(model.methods):
            if mname in attr_targets and mname != "__init__":
                model.entry_methods.add(mname)
    for model in models:
        if not model.entry_methods and not model.closure_roots:
            continue  # class runs no threads of its own
        roots = model.roots()
        if len(roots) < 2:
            continue
        # attr -> roots that reach a mutation of it
        attr_roots: Dict[str, Set[str]] = {}
        for rname, reached in roots.items():
            for scan_key in reached:
                for attr, kind, lineno, locked in (
                        model.scans[scan_key].mutation_ctx):
                    attr_roots.setdefault(attr, set()).add(rname)
        per_ctx: Dict[str, int] = {}
        for scan_key, scan in sorted(model.scans.items()):
            if scan_key == "__init__" or scan_key.endswith("_locked"):
                continue
            reaching = {r for r, reached in roots.items()
                        if scan_key in reached}
            if not reaching:
                continue
            for attr, kind, lineno, locked in scan.mutation_ctx:
                if locked or attr in model.safe_attrs or _lockish(attr):
                    continue
                if kind in MUTATORS and attr in model.delegate_attrs:
                    continue  # delegated call; the callee class locks
                if len(attr_roots.get(attr, ())) < 2:
                    continue
                if _suppressed(model.mod, lineno):
                    continue
                context = (f"{model.mod.rel}:"
                           f"{model.name}.{scan_key}")
                ordinal = per_ctx.get(f"{context}:{attr}", 0)
                per_ctx[f"{context}:{attr}"] = ordinal + 1
                findings.append(Finding(
                    rule="shared-mutation-unlocked",
                    path=model.mod.rel, line=lineno, severity="error",
                    message=(f"'self.{attr}' mutated ({kind}) with no "
                             f"lock held; reachable from threads "
                             f"{sorted(attr_roots[attr])} — hold the "
                             "object's lock or make the field "
                             "thread-confined"),
                    context=context,
                    detail=f"{attr}:{ordinal}"))
    return findings


# --------------------------------------------------------------------
# lock-order-cycle
# --------------------------------------------------------------------

class _LockOrderScan(ast.NodeVisitor):
    """Per-scope: nested with-lock pairs, direct acquisitions, and call
    sites annotated with the locks held there. Nested defs are separate
    scopes (they run later, outside the enclosing `with`)."""

    def __init__(self, class_name: Optional[str], root: ast.AST):
        self.class_name = class_name
        self.root = root
        self.stack: List[str] = []
        self.acquires: Dict[str, int] = {}       # lock -> first line
        self.pairs: List[Tuple[str, str, int]] = []
        self.calls: List[Tuple[str, str, Tuple[str, ...], int]] = []

    def visit_FunctionDef(self, node):
        if node is not self.root:
            return
        for stmt in node.body:
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_With(self, node: ast.With):
        acquired = []
        for item in node.items:
            name = _qualified_lock_name(item.context_expr, self.class_name)
            self.visit(item.context_expr)
            if name:
                self.acquires.setdefault(name, node.lineno)
                for outer in self.stack + acquired:
                    if outer != name:
                        self.pairs.append((outer, name, node.lineno))
                acquired.append(name)
        self.stack.extend(acquired)
        for stmt in node.body:
            self.visit(stmt)
        if acquired:
            del self.stack[-len(acquired):]

    def visit_Call(self, node: ast.Call):
        func = node.func
        if isinstance(func, ast.Name):
            self.calls.append(("name", func.id, tuple(self.stack),
                               node.lineno))
        elif isinstance(func, ast.Attribute):
            kind = ("self" if isinstance(func.value, ast.Name)
                    and func.value.id == "self" else "attr")
            self.calls.append((kind, func.attr, tuple(self.stack),
                               node.lineno))
        self.generic_visit(node)


def _scopes_for(fn: FuncInfo) -> List[ast.AST]:
    """The function node plus each nested def, as separate scopes."""
    out = [fn.node]
    for node in ast.walk(fn.node):
        if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node is not fn.node):
            out.append(node)
    return out


@rule("lock-order-cycle",
      "the package-wide static lock-acquisition-order graph must be "
      "acyclic (cycles are deadlock candidates)")
def check_lock_order_cycle(ctx: AnalysisContext) -> List[Finding]:
    modules = [m for m in ctx.modules if _analysis_scope(m)]
    cg = CallGraph(modules)
    by_rel: Dict[str, Module] = {m.rel: m for m in modules}

    scans: Dict[FuncInfo, List[_LockOrderScan]] = {}
    for fn in cg.functions:
        fn_scans = []
        for scope in _scopes_for(fn):
            scan = _LockOrderScan(fn.class_name, scope)
            scan.visit(scope)
            fn_scans.append(scan)
        scans[fn] = fn_scans

    def _callees(fn: FuncInfo, kind: str, name: str) -> List[FuncInfo]:
        cands = cg.resolve(fn, kind, name)
        if kind == "attr" and len(cands) > 1:
            return []  # ambiguous cross-object call: runtime prong's job
        return cands

    # transitive may-acquire sets, to fixpoint
    acq: Dict[FuncInfo, Set[str]] = {
        fn: set().union(*(s.acquires for s in fn_scans)) if fn_scans
        else set()
        for fn, fn_scans in scans.items()}
    changed = True
    while changed:
        changed = False
        for fn, fn_scans in scans.items():
            cur = acq[fn]
            before = len(cur)
            for scan in fn_scans:
                for kind, name, _, _ in scan.calls:
                    for callee in _callees(fn, kind, name):
                        cur |= acq.get(callee, set())
            if len(cur) != before:
                changed = True

    # edges: (outer, inner) -> (module rel, context, line)
    edges: Dict[Tuple[str, str], Tuple[str, str, int]] = {}

    def _edge(outer: str, inner: str, fn: FuncInfo, line: int):
        if outer == inner:
            return
        key = (outer, inner)
        if key not in edges:
            edges[key] = (fn.module_rel, f"{fn.module_rel}:{fn.qualname}",
                          line)

    for fn, fn_scans in scans.items():
        for scan in fn_scans:
            for outer, inner, line in scan.pairs:
                _edge(outer, inner, fn, line)
            for kind, name, held, line in scan.calls:
                if not held:
                    continue
                for callee in _callees(fn, kind, name):
                    for inner in acq.get(callee, ()):
                        for outer in held:
                            _edge(outer, inner, fn, line)

    # Tarjan SCC over the lock graph
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str):
        # iterative Tarjan (the lock graph is small, but no recursion
        # limits in a lint pass)
        work = [(v, iter(sorted(graph[v])))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph[w]))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                if len(scc) > 1:
                    sccs.append(sorted(scc))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)

    findings: List[Finding] = []
    for scc in sorted(sccs):
        members = set(scc)
        sites = sorted(
            f"{ctxt} (line {line}): {a} -> {b}"
            for (a, b), (_, ctxt, line) in edges.items()
            if a in members and b in members)
        rel, ctxt, line = min(
            (edges[(a, b)] for (a, b) in edges
             if a in members and b in members),
            key=lambda t: (t[0], t[2]))
        mod = by_rel.get(rel)
        if mod is not None and _suppressed(mod, line):
            continue
        findings.append(Finding(
            rule="lock-order-cycle", path=rel, line=line,
            severity="error",
            message=("lock-acquisition-order cycle "
                     f"{' -> '.join(scc + [scc[0]])} — deadlock "
                     "candidate; edges: " + "; ".join(sites[:4])
                     + ("; ..." if len(sites) > 4 else "")),
            context=ctxt,
            detail="|".join(scc)))
    return findings


# --------------------------------------------------------------------
# condvar protocol lints (nomadcheck static prong; see modelcheck.py
# for the dynamic scheduler that explores what these rules approximate)
# --------------------------------------------------------------------

# names that read as a shutdown/lifecycle gate: a wait loop or queue
# handoff that consults one of these has a way to terminate
STOP_NAME_TOKENS = ("stop", "enabled", "enable", "closed", "close",
                    "done", "shut", "running", "quit", "exit", "drain",
                    "cancel", "alive", "dead")

# attribute-call names that are reads/infrastructure, not state
# mutation, for the lost-signal heuristic
_NON_EVIDENCE_METHODS = {
    "wait", "wait_for", "notify", "notify_all", "acquire", "release",
    "locked", "is_set", "is_alive", "debug", "info", "warning", "error",
    "exception", "log", "get", "items", "keys", "values", "copy",
    "time", "monotonic", "sleep", "join", "format", "startswith",
    "endswith", "lower", "upper", "count", "index",
}


def _stopish(name: str) -> bool:
    return any(tok in name.lower() for tok in STOP_NAME_TOKENS)


def _names_in(node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
    return out


def _while_refs_stopish(wh: ast.While) -> bool:
    return any(_stopish(n) for n in _names_in(wh))


def _while_has_escape(wh: ast.While) -> bool:
    """Return/Raise anywhere inside, or Break belonging to this loop
    (not to a nested While/For)."""
    def scan(stmts, owner_is_wh: bool) -> bool:
        for s in stmts:
            if isinstance(s, (ast.Return, ast.Raise)):
                return True
            if isinstance(s, ast.Break) and owner_is_wh:
                return True
            for field in ("body", "orelse", "finalbody", "handlers"):
                sub = getattr(s, field, None)
                if not sub:
                    continue
                if field == "handlers":
                    for h in sub:
                        if scan(h.body, owner_is_wh
                                and not isinstance(s, (ast.While, ast.For))):
                            return True
                    continue
                inner_owner = owner_is_wh and not isinstance(
                    s, (ast.While, ast.For))
                if scan(sub, inner_owner):
                    return True
        return False
    return scan(wh.body, True)


@dataclass
class _WaitSite:
    base: str                    # dotted receiver, e.g. "self._cond"
    lineno: int
    whiles: List[ast.While]      # enclosing While nodes, outermost first
    has_timeout: bool


@dataclass
class _NotifySite:
    base: str
    method: str                  # "notify" | "notify_all"
    lineno: int
    held: List[str]              # lockish with-contexts held at the site


class _CondvarScan(ast.NodeVisitor):
    """One function scope: condvar wait/notify sites with their
    enclosing while-loops and held lockish `with` contexts, plus
    state-mutation evidence linenos (for the lost-signal heuristic).
    Nested defs are separate scopes (scanned on their own)."""

    def __init__(self, func_node: ast.AST):
        self.root = func_node
        self.with_stack: List[str] = []
        self.while_stack: List[ast.While] = []
        self.waits: List[_WaitSite] = []
        self.notifies: List[_NotifySite] = []
        self.evidence: List[int] = []
        # local `x = threading.Condition(y)` aliases in this scope
        self.local_backing: Dict[str, str] = {}
        for stmt in getattr(func_node, "body", []):
            self.visit(stmt)

    def visit_FunctionDef(self, node):
        if node is self.root:
            for stmt in node.body:
                self.visit(stmt)
        # nested def: separate scope

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_While(self, node: ast.While):
        self.visit(node.test)
        self.while_stack.append(node)
        for stmt in node.body:
            self.visit(stmt)
        self.while_stack.pop()
        for stmt in node.orelse:
            self.visit(stmt)

    def visit_With(self, node: ast.With):
        names: List[str] = []
        for item in node.items:
            self.visit(item.context_expr)
            parts = _dotted_parts(item.context_expr)
            if parts and _lockish(parts[-1]):
                names.append(".".join(parts))
        self.with_stack.extend(names)
        for stmt in node.body:
            self.visit(stmt)
        if names:
            del self.with_stack[-len(names):]

    def _mark(self, lineno: int):
        self.evidence.append(lineno)

    def visit_Assign(self, node: ast.Assign):
        self._mark(node.lineno)
        if (isinstance(node.value, ast.Call)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            parts = _dotted_parts(node.value.func)
            if parts and parts[-1] == "Condition":
                arg_parts = (_dotted_parts(node.value.args[0])
                             if node.value.args else None)
                self.local_backing[node.targets[0].id] = (
                    ".".join(arg_parts) if arg_parts
                    else node.targets[0].id)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._mark(node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._mark(node.lineno)
        self.generic_visit(node)

    def visit_Delete(self, node):
        self._mark(node.lineno)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        parts = _dotted_parts(node.func)
        if parts and len(parts) >= 2:
            meth = parts[-1]
            base = ".".join(parts[:-1])
            if _lockish(parts[-2]):
                if meth == "wait":
                    self.waits.append(_WaitSite(
                        base, node.lineno, list(self.while_stack),
                        bool(node.args or node.keywords)))
                elif meth in ("notify", "notify_all"):
                    self.notifies.append(_NotifySite(
                        base, meth, node.lineno, list(self.with_stack)))
            if meth not in _NON_EVIDENCE_METHODS:
                self._mark(node.lineno)
        self.generic_visit(node)


@dataclass
class _CondScope:
    """A function scope prepared for the condvar rules."""
    mod: Module
    context: str                 # qualname-ish context string
    class_name: Optional[str]
    method_name: str
    node: ast.AST
    scan: _CondvarScan


def _cond_backing_map(class_node: Optional[ast.ClassDef]) -> Dict[str, str]:
    """self-attr condvar -> self-attr backing lock, from __init__
    (`self.c = threading.Condition(self._lock)`; a Condition() with no
    arg backs itself)."""
    out: Dict[str, str] = {}
    if class_node is None:
        return out
    init = next((s for s in class_node.body
                 if isinstance(s, ast.FunctionDef)
                 and s.name == "__init__"), None)
    if init is None:
        return out
    for node in ast.walk(init):
        if not isinstance(node, ast.Assign):
            continue
        if not isinstance(node.value, ast.Call):
            continue
        parts = _dotted_parts(node.value.func)
        if not parts or parts[-1] != "Condition":
            continue
        for t in node.targets:
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                arg = node.value.args[0] if node.value.args else None
                arg_parts = _dotted_parts(arg) if arg is not None else None
                if (arg_parts and arg_parts[0] == "self"
                        and len(arg_parts) == 2):
                    out[t.attr] = arg_parts[1]
                else:
                    out[t.attr] = t.attr
    return out


def _stopish_attr_in_init(class_node: Optional[ast.ClassDef]) -> bool:
    """Does __init__ bind any lifecycle-gate attribute (self._stop,
    self._enabled, self._closed, ...)? Classes with no close concept
    are exempt from the queue-handoff rules."""
    if class_node is None:
        return False
    init = next((s for s in class_node.body
                 if isinstance(s, ast.FunctionDef)
                 and s.name == "__init__"), None)
    if init is None:
        return False
    for node in ast.walk(init):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self" and _stopish(t.attr)):
                    return True
    return False


def _cond_scopes(ctx: AnalysisContext) -> List[Tuple[_CondScope,
                                                     Optional[ast.ClassDef]]]:
    """Every function/method/nested-closure scope in analysis scope,
    paired with its owning top-level class (None for module funcs)."""
    out: List[Tuple[_CondScope, Optional[ast.ClassDef]]] = []

    def add_scope(mod, fn, class_node, prefix):
        ctxt = f"{mod.rel}:{prefix}{fn.name}"
        out.append((_CondScope(mod, ctxt, class_node.name if class_node
                               else None, fn.name, fn, _CondvarScan(fn)),
                    class_node))
        for inner in ast.walk(fn):
            if (isinstance(inner, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and inner is not fn):
                ictxt = f"{mod.rel}:{prefix}{fn.name}.{inner.name}"
                out.append((_CondScope(
                    mod, ictxt, class_node.name if class_node else None,
                    inner.name, inner, _CondvarScan(inner)), class_node))

    for mod in ctx.modules:
        if not _analysis_scope(mod):
            continue
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                add_scope(mod, node, None, "")
            elif isinstance(node, ast.ClassDef):
                for s in node.body:
                    if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        add_scope(mod, s, node, f"{node.name}.")
    return out


@rule("condvar-wait-outside-loop",
      "Condition.wait() not wrapped in a predicate-rechecking while "
      "loop (spurious/stolen wakeups break the caller)")
def check_condvar_wait_outside_loop(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    per_ctx: Dict[str, int] = {}
    for scope, _cls in _cond_scopes(ctx):
        for w in scope.scan.waits:
            if w.whiles:
                continue
            if _suppressed(scope.mod, w.lineno):
                continue
            key = f"{scope.context}:{w.base}"
            ordinal = per_ctx.get(key, 0)
            per_ctx[key] = ordinal + 1
            findings.append(Finding(
                rule="condvar-wait-outside-loop",
                path=scope.mod.rel, line=w.lineno, severity="error",
                message=(f"'{w.base}.wait()' outside a while loop — a "
                         "spurious or stolen wakeup returns with the "
                         "predicate false; wrap in "
                         "'while not <predicate>: ...wait()'"),
                context=scope.context,
                detail=f"{w.base}:{ordinal}"))
    return findings


@rule("condvar-notify-unlocked",
      "notify/notify_all without the condvar's (or its backing) lock "
      "held — the waiter can miss the signal")
def check_condvar_notify_unlocked(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    per_ctx: Dict[str, int] = {}
    backing_cache: Dict[int, Dict[str, str]] = {}
    for scope, cls in _cond_scopes(ctx):
        if scope.method_name.endswith("_locked"):
            continue  # caller owns the lock by convention
        if cls is not None and id(cls) not in backing_cache:
            backing_cache[id(cls)] = _cond_backing_map(cls)
        backing = backing_cache.get(id(cls), {}) if cls else {}
        for n in scope.scan.notifies:
            allowed = {n.base}
            if n.base.startswith("self."):
                attr = n.base[len("self."):]
                back = backing.get(attr, attr)
                allowed.add(f"self.{back}")
                for sib, b in backing.items():
                    if b == back:
                        allowed.add(f"self.{sib}")
            else:
                back = scope.scan.local_backing.get(n.base)
                if back:
                    allowed.add(back)
            if any(h in allowed for h in n.held):
                continue
            if _suppressed(scope.mod, n.lineno):
                continue
            key = f"{scope.context}:{n.base}"
            ordinal = per_ctx.get(key, 0)
            per_ctx[key] = ordinal + 1
            findings.append(Finding(
                rule="condvar-notify-unlocked",
                path=scope.mod.rel, line=n.lineno, severity="error",
                message=(f"'{n.base}.{n.method}()' with no associated "
                         "lock held — a waiter between its predicate "
                         "check and wait() misses this signal; wrap in "
                         f"'with {n.base}:' (or the backing lock)"),
                context=scope.context,
                detail=f"{n.base}:{ordinal}"))
    return findings


@rule("condvar-lost-signal",
      "notify with no preceding shared-state mutation in the function "
      "— the woken waiter re-checks its predicate and sleeps again")
def check_condvar_lost_signal(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    per_ctx: Dict[str, int] = {}
    for scope, _cls in _cond_scopes(ctx):
        if scope.method_name.endswith("_locked"):
            continue  # protocol owned by callers
        for n in scope.scan.notifies:
            if any(ln < n.lineno for ln in scope.scan.evidence):
                continue
            if _suppressed(scope.mod, n.lineno):
                continue
            key = f"{scope.context}:{n.base}"
            ordinal = per_ctx.get(key, 0)
            per_ctx[key] = ordinal + 1
            findings.append(Finding(
                rule="condvar-lost-signal",
                path=scope.mod.rel, line=n.lineno, severity="warning",
                message=(f"'{n.base}.{n.method}()' with no shared-state "
                         "mutation earlier in this function — waiters "
                         "wake, find their predicate unchanged, and "
                         "sleep again (signal does nothing); mutate "
                         "the guarded state before notifying"),
                context=scope.context,
                detail=f"{n.base}:{ordinal}"))
    return findings


@rule("condvar-wait-no-shutdown-check",
      "wait loop with no shutdown sentinel and no bounded escape — "
      "the thread can never be joined (drain-without-sentinel)")
def check_condvar_wait_no_shutdown(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    per_ctx: Dict[str, int] = {}
    for scope, _cls in _cond_scopes(ctx):
        for w in scope.scan.waits:
            if not w.whiles:
                continue  # condvar-wait-outside-loop's finding
            if any(_while_refs_stopish(wh) for wh in w.whiles):
                continue
            if w.has_timeout and _while_has_escape(w.whiles[-1]):
                continue  # bounded wait with an exit path
            if _suppressed(scope.mod, w.lineno):
                continue
            key = f"{scope.context}:{w.base}"
            ordinal = per_ctx.get(key, 0)
            per_ctx[key] = ordinal + 1
            findings.append(Finding(
                rule="condvar-wait-no-shutdown-check",
                path=scope.mod.rel, line=w.lineno, severity="error",
                message=(f"wait loop on '{w.base}' checks no shutdown "
                         "sentinel (stop/enabled/closed/...) and has "
                         "no timed escape — shutdown must wake AND "
                         "terminate this loop or join() hangs"),
                context=scope.context,
                detail=f"{w.base}:{ordinal}"))
    return findings


@rule("thread-no-shutdown-join",
      "class spawns threads/timers but no method joins, cancels, or "
      "signals them to stop — leaked on shutdown")
def check_thread_no_shutdown_join(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for mod in ctx.modules:
        if not _analysis_scope(mod):
            continue
        for node in mod.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            model = _ClassModel(mod, node)
            spawns: List[int] = []
            for mname, mnode in model.methods.items():
                for call in ast.walk(mnode):
                    if (isinstance(call, ast.Call)
                            and _thread_target_expr(call) is not None):
                        spawns.append(call.lineno)
            if not spawns:
                continue
            has_shutdown = False
            for mname, mnode in model.methods.items():
                for call in ast.walk(mnode):
                    if isinstance(call, ast.Assign):
                        for t in call.targets:
                            if (isinstance(t, ast.Attribute)
                                    and isinstance(t.value, ast.Name)
                                    and t.value.id == "self"
                                    and _stopish(t.attr)):
                                has_shutdown = True
                    if not isinstance(call, ast.Call):
                        continue
                    parts = _dotted_parts(call.func)
                    if not parts or len(parts) < 2:
                        continue
                    meth = parts[-1]
                    if meth in ("join", "cancel", "shutdown"):
                        has_shutdown = True
                    elif (meth in ("set", "clear")
                          and _stopish(parts[-2])):
                        has_shutdown = True
                if has_shutdown:
                    break
            if has_shutdown:
                continue
            line = spawns[0]
            if _suppressed(mod, line) or _suppressed(mod, node.lineno):
                continue
            findings.append(Finding(
                rule="thread-no-shutdown-join",
                path=mod.rel, line=line, severity="error",
                message=(f"class '{node.name}' spawns threads/timers "
                         "but no method joins, cancels, or sets a "
                         "stop flag for them — add a stop()/close() "
                         "that shuts the threads down"),
                context=f"{mod.rel}:{node.name}",
                detail=node.name))
    return findings


@rule("queue-enqueue-no-close-check",
      "queue handoff (append + notify) with no lifecycle-gate read — "
      "items enqueued after close are silently lost")
def check_queue_enqueue_no_close(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for scope, cls in _cond_scopes(ctx):
        if (scope.method_name.endswith("_locked")
                or scope.method_name == "__init__"):
            continue
        if cls is None or not _stopish_attr_in_init(cls):
            continue  # class has no close concept to race with
        if not scope.scan.notifies:
            continue
        # append-shaped mutation: MUTATORS call on a self attr,
        # heapq.heappush(self.x, ...), or self.x[k] = v
        appends: List[int] = []
        for n in ast.walk(scope.node):
            if isinstance(n, ast.Call):
                parts = _dotted_parts(n.func)
                if (parts and len(parts) >= 2 and parts[-1] in
                        ("append", "appendleft", "add", "insert")
                        and parts[0] == "self"):
                    appends.append(n.lineno)
                elif (parts and parts[-1] == "heappush" and n.args
                      and (_dotted_parts(n.args[0]) or [""])[0]
                      == "self"):
                    appends.append(n.lineno)
            elif isinstance(n, ast.Assign):
                for t in n.targets:
                    if (isinstance(t, ast.Subscript)
                            and (_dotted_parts(t.value) or [""])[0]
                            == "self"):
                        appends.append(n.lineno)
        if not appends:
            continue
        if any(_stopish(name) for name in _names_in(scope.node)):
            continue  # gate consulted somewhere in the method
        line = appends[0]
        if _suppressed(scope.mod, line):
            continue
        findings.append(Finding(
            rule="queue-enqueue-no-close-check",
            path=scope.mod.rel, line=line, severity="error",
            message=("queue handoff (append + notify) never reads the "
                     "class's lifecycle gate — an enqueue racing "
                     "close/stop strands the item with no consumer; "
                     "check the stop/enabled flag under the lock"),
            context=scope.context,
            detail=scope.method_name))
    return findings
