"""nomadsan static prong: thread entrypoints, lock regions, two rules.

`shared-mutation-unlocked` — the control plane is ~25 threaded modules
whose objects are mutated from watcher loops, worker pools, timers and
the caller's thread. Per class, this rule discovers every thread
entrypoint (``threading.Thread(target=self.x)``, ``threading.Timer``,
executor ``.submit``, thread-spawned closures), adds the public-method
surface as one collective "api" root (public methods may be called from
any thread), computes which methods each root reaches via self-calls,
and flags any ``self.attr`` mutation site that (a) sits in a method
reachable from >= 2 distinct roots of a class that actually runs
threads and (b) holds no lock at the mutation site. Attributes bound to
thread-safe primitives in ``__init__`` (locks, events, queues, deques)
are exempt, as are ``__init__`` itself and methods following the
``*_locked`` suffix convention (their callers own the lock).

`lock-order-cycle` — the static generalization of PR 1's pairwise
``lock-order`` rule: build the package-wide lock-acquisition-order
graph (lock names qualified by class, so ``EvalBroker._lock`` and
``PlanQueue._lock`` are distinct nodes), including interprocedural
edges — a function holding L that calls ``g()`` points L at every lock
``g`` transitively acquires — and flag every cycle as a deadlock
candidate. Attribute-kind calls (``obj.m()``) are followed only when
the name resolves uniquely in the tree; anything noisier is the runtime
prong's job (sanitizer.py).

False positives are suppressed in code with a ``# san-ok: <why>``
comment on the flagged line (or the line above), never baselined — the
justification lives next to the code it excuses.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import CallGraph, FuncInfo
from .core import AnalysisContext, Finding, Module, rule

SUPPRESS_TOKEN = "san-ok:"

# attribute-call names that mutate the receiver container in place
MUTATORS = {
    "append", "appendleft", "add", "insert", "extend", "update",
    "pop", "popitem", "popleft", "remove", "discard", "clear",
    "setdefault", "sort", "reverse",
}

# constructors whose instances are internally synchronized: attributes
# bound to these in __init__ are not "shared mutable state"
THREADSAFE_CTORS = {
    "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
    "Event", "Barrier", "Queue", "LifoQueue", "PriorityQueue",
    "SimpleQueue", "deque", "local",
}

# plain-container constructors: a mutator-method call (`self.x.add(...)`)
# only counts as container mutation when __init__ binds the attribute to
# one of these (or a display literal). Anything else — e.g.
# `self.periodic = PeriodicDispatcher(...)` — is a delegated call to an
# object that owns its own locking and is analyzed on its own.
CONTAINER_CTORS = {
    "dict", "list", "set", "defaultdict", "OrderedDict", "Counter",
    "ChainMap",
}

LOCK_NAME_TOKENS = ("lock", "cond", "mutex", "sem")


def _analysis_scope(mod: Module) -> bool:
    """Everything in the package except the analyzer itself; fixture
    trees (outside nomad_tpu) are always in scope so rules are testable
    on standalone snippets."""
    from pathlib import Path

    parts = Path(mod.rel).parts
    if "nomad_tpu" not in parts:
        return True
    i = parts.index("nomad_tpu")
    return not (len(parts) > i + 1 and parts[i + 1] == "analysis")


def _suppressed(mod: Module, lineno: int) -> bool:
    lines = mod.source.splitlines()
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(lines) and SUPPRESS_TOKEN in lines[ln - 1]:
            return True
    return False


def _dotted_parts(node: ast.expr) -> Optional[List[str]]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None


def _lockish(name: str) -> bool:
    return any(tok in name.lower() for tok in LOCK_NAME_TOKENS)


def _qualified_lock_name(expr: ast.expr, class_name: Optional[str]) -> str:
    """Class-qualified dotted name of a lock-ish `with` context, or "".
    `self._lock` in class C -> "C._lock" (distinct graph nodes per
    class); bare/module locks keep their dotted spelling."""
    parts = _dotted_parts(expr)
    if not parts or not _lockish(parts[-1]):
        return ""
    if parts[0] == "self":
        parts = parts[1:]
        if class_name:
            parts = [class_name] + parts
    return ".".join(parts)


# --------------------------------------------------------------------
# thread-entrypoint discovery
# --------------------------------------------------------------------

@dataclass(frozen=True)
class ThreadSite:
    module_rel: str
    lineno: int
    factory: str                 # "Thread" | "Timer" | "submit"
    target: str                  # source-ish description of the callable


def _thread_target_expr(call: ast.Call) -> Optional[Tuple[str, ast.expr]]:
    """(factory, target-callable expr) for thread-spawning calls."""
    func = call.func
    name = func.attr if isinstance(func, ast.Attribute) else (
        func.id if isinstance(func, ast.Name) else "")
    if name == "Thread":
        for kw in call.keywords:
            if kw.arg == "target":
                return "Thread", kw.value
        return None
    if name == "Timer":
        # Timer(interval, function, ...)
        if len(call.args) >= 2:
            return "Timer", call.args[1]
        for kw in call.keywords:
            if kw.arg == "function":
                return "Timer", kw.value
        return None
    if name == "submit" and isinstance(func, ast.Attribute) and call.args:
        return "submit", call.args[0]
    return None


def discover_thread_sites(modules: List[Module]) -> List[ThreadSite]:
    """Every Thread/Timer/executor-submit spawn site in the tree (the
    pass `python -m nomad_tpu.analysis --threads` dumps)."""
    sites: List[ThreadSite] = []
    for mod in modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            hit = _thread_target_expr(node)
            if hit is None:
                continue
            factory, target = hit
            parts = _dotted_parts(target)
            desc = ".".join(parts) if parts else (
                "<lambda>" if isinstance(target, ast.Lambda) else
                ast.unparse(target) if hasattr(ast, "unparse") else "<expr>")
            sites.append(ThreadSite(mod.rel, node.lineno, factory, desc))
    return sites


# --------------------------------------------------------------------
# shared-mutation-unlocked
# --------------------------------------------------------------------

@dataclass
class _Mutation:
    attr: str
    kind: str        # "assign" | "subscript" | mutator method name
    lineno: int
    locked: bool     # any lock-named `with` encloses the site
    method: str      # owning method name (or "method.closure")


class _MethodScan(ast.NodeVisitor):
    """One pass over a method scope: self-call edges, self.attr
    mutations with held-lock context, thread spawns. Nested defs that
    are thread targets are excluded (they are their own root scope);
    other closures stay attributed to the enclosing method (they may
    run inline)."""

    def __init__(self, skip_defs: Set[ast.AST]):
        self.skip_defs = skip_defs
        self.self_calls: Set[str] = set()
        self.mutations: List[Tuple[str, str, int]] = []  # (attr, kind, line)
        self.locked_lines: List[Tuple[int, int]] = []    # with-lock spans
        self._lock_depth = 0
        self.mutation_ctx: List[Tuple[str, str, int, bool]] = []

    def visit_FunctionDef(self, node):
        if node in self.skip_defs:
            return
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_With(self, node: ast.With):
        lockish = any(
            _lockish((_dotted_parts(item.context_expr) or ["?"])[-1])
            for item in node.items
            if _dotted_parts(item.context_expr))
        for item in node.items:
            self.visit(item.context_expr)
        if lockish:
            self._lock_depth += 1
        for stmt in node.body:
            self.visit(stmt)
        if lockish:
            self._lock_depth -= 1

    def _self_attr(self, expr: ast.expr) -> Optional[str]:
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            return expr.attr
        return None

    def _note(self, attr: str, kind: str, lineno: int):
        self.mutation_ctx.append((attr, kind, lineno, self._lock_depth > 0))

    def _check_target(self, target: ast.expr, lineno: int):
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._check_target(elt, lineno)
            return
        attr = self._self_attr(target)
        if attr is not None:
            self._note(attr, "assign", lineno)
            return
        if isinstance(target, ast.Subscript):
            attr = self._self_attr(target.value)
            if attr is not None:
                self._note(attr, "subscript", lineno)

    def visit_Assign(self, node: ast.Assign):
        for target in node.targets:
            self._check_target(target, node.lineno)
        self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign):
        self._check_target(node.target, node.lineno)
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            self._check_target(node.target, node.lineno)
            self.visit(node.value)

    def visit_Delete(self, node: ast.Delete):
        for target in node.targets:
            if isinstance(target, ast.Subscript):
                attr = self._self_attr(target.value)
                if attr is not None:
                    self._note(attr, "subscript", node.lineno)
            elif (attr := self._self_attr(target)) is not None:
                self._note(attr, "assign", node.lineno)

    def visit_Call(self, node: ast.Call):
        func = node.func
        if isinstance(func, ast.Attribute):
            if func.attr in MUTATORS:
                attr = self._self_attr(func.value)
                if attr is not None:
                    self._note(attr, func.attr, node.lineno)
            if (isinstance(func.value, ast.Name)
                    and func.value.id == "self"):
                self.self_calls.add(func.attr)
        self.generic_visit(node)


def _init_attr_kinds(init_node: Optional[ast.AST]
                     ) -> Tuple[Set[str], Set[str], Set[str]]:
    """(threadsafe, container, other-call) attribute sets from __init__
    assignments. Attrs never assigned in __init__ land in none of them
    (treated as containers, over-approximately)."""
    safe: Set[str] = set()
    containers: Set[str] = set()
    delegates: Set[str] = set()
    if init_node is None:
        return safe, containers, delegates
    for node in ast.walk(init_node):
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        attrs = [t.attr for t in node.targets
                 if isinstance(t, ast.Attribute)
                 and isinstance(t.value, ast.Name) and t.value.id == "self"]
        if not attrs:
            continue
        if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                              ast.DictComp, ast.SetComp)):
            containers.update(attrs)
        elif isinstance(value, ast.Call):
            parts = _dotted_parts(value.func)
            ctor = parts[-1] if parts else ""
            if ctor in THREADSAFE_CTORS:
                safe.update(attrs)
            elif ctor in CONTAINER_CTORS:
                containers.update(attrs)
            else:
                delegates.update(attrs)
    return safe, containers, delegates


class _ClassModel:
    def __init__(self, mod: Module, node: ast.ClassDef):
        self.mod = mod
        self.node = node
        self.name = node.name
        self.methods: Dict[str, ast.AST] = {
            s.name: s for s in node.body
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))}
        (self.safe_attrs, self.container_attrs,
         self.delegate_attrs) = _init_attr_kinds(self.methods.get("__init__"))
        # thread-target closures: nested def nodes spawned as threads
        self.closure_roots: Dict[str, Tuple[str, ast.AST]] = {}
        # method-name entrypoints via self.<m> targets inside this class
        self.entry_methods: Set[str] = set()
        self._discover_spawns()
        self.scans: Dict[str, _MethodScan] = {}
        skip = {node for _, node in self.closure_roots.values()}
        for mname, mnode in self.methods.items():
            scan = _MethodScan(skip)
            for stmt in mnode.body:
                scan.visit(stmt)
            self.scans[mname] = scan
        for rname, (owner, cnode) in self.closure_roots.items():
            scan = _MethodScan(set())
            for stmt in cnode.body:
                scan.visit(stmt)
            self.scans[rname] = scan

    def _discover_spawns(self):
        for mname, mnode in self.methods.items():
            nested = {n.name: n for n in ast.walk(mnode)
                      if isinstance(n, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))
                      and n is not mnode}
            for node in ast.walk(mnode):
                if not isinstance(node, ast.Call):
                    continue
                hit = _thread_target_expr(node)
                if hit is None:
                    continue
                _, target = hit
                parts = _dotted_parts(target)
                if parts and parts[0] == "self" and len(parts) == 2:
                    if parts[1] in self.methods:
                        self.entry_methods.add(parts[1])
                elif (isinstance(target, ast.Name)
                      and target.id in nested):
                    root = f"{mname}.{target.id}"
                    self.closure_roots[root] = (mname, nested[target.id])

    def roots(self) -> Dict[str, Set[str]]:
        """root name -> set of scan keys (methods/closures) it reaches
        via self-calls."""
        out: Dict[str, Set[str]] = {}
        # a public method that IS a thread entrypoint (e.g. Worker.run)
        # is excluded from the collective api root: calling it directly
        # while it also runs as the thread is a usage error, not a race
        public = {m for m in self.methods
                  if not m.startswith("_") and m != "__init__"
                  and m not in self.entry_methods}

        def reach(seed: Set[str]) -> Set[str]:
            seen: Set[str] = set()
            frontier = [s for s in seed if s in self.scans]
            while frontier:
                cur = frontier.pop()
                if cur in seen:
                    continue
                seen.add(cur)
                for callee in self.scans[cur].self_calls:
                    if callee in self.scans and callee not in seen:
                        frontier.append(callee)
            return seen

        if public:
            out["api"] = reach(public)
        for m in self.entry_methods:
            out[f"thread:{m}"] = reach({m})
        for rname in self.closure_roots:
            seen = reach({rname})
            seen |= reach(self.scans[rname].self_calls)
            seen.add(rname)
            out[f"thread:{rname}"] = seen
        return out


@rule("shared-mutation-unlocked",
      "self.attr mutation reachable from >=2 thread roots with no lock "
      "held at the site")
def check_shared_mutation_unlocked(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    models: List[_ClassModel] = []
    # global pass: `target=obj.m` spawns outside the class mark every
    # class owning method m as threaded via that entrypoint
    attr_targets: Set[str] = set()
    modules = [m for m in ctx.modules if _analysis_scope(m)]
    for mod in modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                hit = _thread_target_expr(node)
                if hit is None:
                    continue
                parts = _dotted_parts(hit[1])
                if parts and parts[0] != "self" and len(parts) >= 2:
                    attr_targets.add(parts[-1])
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                models.append(_ClassModel(mod, node))
    for model in models:
        for mname in list(model.methods):
            if mname in attr_targets and mname != "__init__":
                model.entry_methods.add(mname)
    for model in models:
        if not model.entry_methods and not model.closure_roots:
            continue  # class runs no threads of its own
        roots = model.roots()
        if len(roots) < 2:
            continue
        # attr -> roots that reach a mutation of it
        attr_roots: Dict[str, Set[str]] = {}
        for rname, reached in roots.items():
            for scan_key in reached:
                for attr, kind, lineno, locked in (
                        model.scans[scan_key].mutation_ctx):
                    attr_roots.setdefault(attr, set()).add(rname)
        per_ctx: Dict[str, int] = {}
        for scan_key, scan in sorted(model.scans.items()):
            if scan_key == "__init__" or scan_key.endswith("_locked"):
                continue
            reaching = {r for r, reached in roots.items()
                        if scan_key in reached}
            if not reaching:
                continue
            for attr, kind, lineno, locked in scan.mutation_ctx:
                if locked or attr in model.safe_attrs or _lockish(attr):
                    continue
                if kind in MUTATORS and attr in model.delegate_attrs:
                    continue  # delegated call; the callee class locks
                if len(attr_roots.get(attr, ())) < 2:
                    continue
                if _suppressed(model.mod, lineno):
                    continue
                context = (f"{model.mod.rel}:"
                           f"{model.name}.{scan_key}")
                ordinal = per_ctx.get(f"{context}:{attr}", 0)
                per_ctx[f"{context}:{attr}"] = ordinal + 1
                findings.append(Finding(
                    rule="shared-mutation-unlocked",
                    path=model.mod.rel, line=lineno, severity="error",
                    message=(f"'self.{attr}' mutated ({kind}) with no "
                             f"lock held; reachable from threads "
                             f"{sorted(attr_roots[attr])} — hold the "
                             "object's lock or make the field "
                             "thread-confined"),
                    context=context,
                    detail=f"{attr}:{ordinal}"))
    return findings


# --------------------------------------------------------------------
# lock-order-cycle
# --------------------------------------------------------------------

class _LockOrderScan(ast.NodeVisitor):
    """Per-scope: nested with-lock pairs, direct acquisitions, and call
    sites annotated with the locks held there. Nested defs are separate
    scopes (they run later, outside the enclosing `with`)."""

    def __init__(self, class_name: Optional[str], root: ast.AST):
        self.class_name = class_name
        self.root = root
        self.stack: List[str] = []
        self.acquires: Dict[str, int] = {}       # lock -> first line
        self.pairs: List[Tuple[str, str, int]] = []
        self.calls: List[Tuple[str, str, Tuple[str, ...], int]] = []

    def visit_FunctionDef(self, node):
        if node is not self.root:
            return
        for stmt in node.body:
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_With(self, node: ast.With):
        acquired = []
        for item in node.items:
            name = _qualified_lock_name(item.context_expr, self.class_name)
            self.visit(item.context_expr)
            if name:
                self.acquires.setdefault(name, node.lineno)
                for outer in self.stack + acquired:
                    if outer != name:
                        self.pairs.append((outer, name, node.lineno))
                acquired.append(name)
        self.stack.extend(acquired)
        for stmt in node.body:
            self.visit(stmt)
        if acquired:
            del self.stack[-len(acquired):]

    def visit_Call(self, node: ast.Call):
        func = node.func
        if isinstance(func, ast.Name):
            self.calls.append(("name", func.id, tuple(self.stack),
                               node.lineno))
        elif isinstance(func, ast.Attribute):
            kind = ("self" if isinstance(func.value, ast.Name)
                    and func.value.id == "self" else "attr")
            self.calls.append((kind, func.attr, tuple(self.stack),
                               node.lineno))
        self.generic_visit(node)


def _scopes_for(fn: FuncInfo) -> List[ast.AST]:
    """The function node plus each nested def, as separate scopes."""
    out = [fn.node]
    for node in ast.walk(fn.node):
        if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node is not fn.node):
            out.append(node)
    return out


@rule("lock-order-cycle",
      "the package-wide static lock-acquisition-order graph must be "
      "acyclic (cycles are deadlock candidates)")
def check_lock_order_cycle(ctx: AnalysisContext) -> List[Finding]:
    modules = [m for m in ctx.modules if _analysis_scope(m)]
    cg = CallGraph(modules)
    by_rel: Dict[str, Module] = {m.rel: m for m in modules}

    scans: Dict[FuncInfo, List[_LockOrderScan]] = {}
    for fn in cg.functions:
        fn_scans = []
        for scope in _scopes_for(fn):
            scan = _LockOrderScan(fn.class_name, scope)
            scan.visit(scope)
            fn_scans.append(scan)
        scans[fn] = fn_scans

    def _callees(fn: FuncInfo, kind: str, name: str) -> List[FuncInfo]:
        cands = cg.resolve(fn, kind, name)
        if kind == "attr" and len(cands) > 1:
            return []  # ambiguous cross-object call: runtime prong's job
        return cands

    # transitive may-acquire sets, to fixpoint
    acq: Dict[FuncInfo, Set[str]] = {
        fn: set().union(*(s.acquires for s in fn_scans)) if fn_scans
        else set()
        for fn, fn_scans in scans.items()}
    changed = True
    while changed:
        changed = False
        for fn, fn_scans in scans.items():
            cur = acq[fn]
            before = len(cur)
            for scan in fn_scans:
                for kind, name, _, _ in scan.calls:
                    for callee in _callees(fn, kind, name):
                        cur |= acq.get(callee, set())
            if len(cur) != before:
                changed = True

    # edges: (outer, inner) -> (module rel, context, line)
    edges: Dict[Tuple[str, str], Tuple[str, str, int]] = {}

    def _edge(outer: str, inner: str, fn: FuncInfo, line: int):
        if outer == inner:
            return
        key = (outer, inner)
        if key not in edges:
            edges[key] = (fn.module_rel, f"{fn.module_rel}:{fn.qualname}",
                          line)

    for fn, fn_scans in scans.items():
        for scan in fn_scans:
            for outer, inner, line in scan.pairs:
                _edge(outer, inner, fn, line)
            for kind, name, held, line in scan.calls:
                if not held:
                    continue
                for callee in _callees(fn, kind, name):
                    for inner in acq.get(callee, ()):
                        for outer in held:
                            _edge(outer, inner, fn, line)

    # Tarjan SCC over the lock graph
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str):
        # iterative Tarjan (the lock graph is small, but no recursion
        # limits in a lint pass)
        work = [(v, iter(sorted(graph[v])))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph[w]))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                if len(scc) > 1:
                    sccs.append(sorted(scc))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)

    findings: List[Finding] = []
    for scc in sorted(sccs):
        members = set(scc)
        sites = sorted(
            f"{ctxt} (line {line}): {a} -> {b}"
            for (a, b), (_, ctxt, line) in edges.items()
            if a in members and b in members)
        rel, ctxt, line = min(
            (edges[(a, b)] for (a, b) in edges
             if a in members and b in members),
            key=lambda t: (t[0], t[2]))
        mod = by_rel.get(rel)
        if mod is not None and _suppressed(mod, line):
            continue
        findings.append(Finding(
            rule="lock-order-cycle", path=rel, line=line,
            severity="error",
            message=("lock-acquisition-order cycle "
                     f"{' -> '.join(scc + [scc[0]])} — deadlock "
                     "candidate; edges: " + "; ".join(sites[:4])
                     + ("; ..." if len(sites) > 4 else "")),
            context=ctxt,
            detail="|".join(scc)))
    return findings
