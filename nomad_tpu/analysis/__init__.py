"""nomadlint: AST-based invariant checker for this codebase.

The test suite cannot see two invariant classes this package
machine-checks on every run (see ANALYSIS.md at the repo root):

- replica determinism: everything reachable from the raft FSM apply
  dispatch must be a pure function of the replicated command
  (`fsm-determinism`, `shared-struct-mutation`);
- hot-path health: the JAX scheduling kernels must stay free of host
  syncs and retrace traps (`jax-hot-path`), errors must not vanish
  (`silent-except`), and lock pairs must nest one way (`lock-order`).

Run `python -m nomad_tpu.analysis`; the gate is zero findings beyond
the checked-in `baseline.json` allowlist.
"""

from .core import (AnalysisContext, Finding, all_rules, baseline_path,
                   load_baseline, partition, run_analysis, write_baseline)

__all__ = [
    "AnalysisContext", "Finding", "all_rules", "baseline_path",
    "load_baseline", "partition", "run_analysis", "write_baseline",
]
