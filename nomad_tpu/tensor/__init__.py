"""Tensor layer: snapshot -> dense tensors -> JAX placement kernels.

The TPU-native core of the framework (SURVEY.md §7 stages 2-4). The host
scheduler path evaluates one (eval x node) at a time through an iterator
chain (reference scheduler/stack.go); this layer lowers a whole batch of
placements x all nodes to dense arrays and solves placement as one fused,
jittable program:

- cluster.py  — tensorization: nodes/usage/constraints/spreads -> arrays
- kernels.py  — the jitted score + sequential-argmax assignment kernels
- placer.py   — TPUPlacer: the Placer implementation behind
                SchedulerAlgorithm="tpu-binpack"
- sharding.py — multi-chip mesh layouts for the node axis
"""

from .placer import TPUPlacer

__all__ = ["TPUPlacer"]
