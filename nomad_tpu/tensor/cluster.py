"""Tensorization: snapshot + in-progress plan -> dense arrays.

The piece with no reference analog (SURVEY.md §7 stage 2): lowers the
object-graph view the host scheduler walks (nodes, proposed allocs,
constraints, spreads) into the padded arrays kernels.py consumes.

Constraint semantics stay host-side — regex/version/semver operators are
evaluated once per *unique attribute value* by the vectorized masks in
scheduler.feasible (the tensor-era form of the reference's computed-node-
class memoization, context.go:261) — and only the resulting boolean masks
and interned value-id tables ship to the device.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..structs import Job, Node, TaskGroup, enums
from ..structs.resources import RESOURCE_DIMS
from ..scheduler.context import EvalContext
from ..scheduler.feasible import (
    check_constraint,
    distinct_hosts_flags,
    feasible_mask,
    reserved_ports_mask,
    resolve_target,
)
from ..scheduler.spread import IMPLICIT_TARGET, SpreadInfo, combined_spreads


def _pad_pow2(n: int, floor: int = 8) -> int:
    out = floor
    while out < n:
        out *= 2
    return out


@dataclass
class ClusterTensors:
    """Per-(eval, node-list) arrays shared by every task group's solve."""

    nodes: List[Node]
    n_pad: int
    available: np.ndarray          # (Np, D)
    used: np.ndarray               # (Np, D) proposed usage
    node_index: Dict[str, int]

    @classmethod
    def build(cls, ctx: EvalContext, nodes: Sequence[Node]) -> "ClusterTensors":
        n = len(nodes)
        n_pad = _pad_pow2(n)
        available = np.zeros((n_pad, RESOURCE_DIMS))
        used = np.zeros((n_pad, RESOURCE_DIMS))
        index: Dict[str, int] = {}
        for i, node in enumerate(nodes):
            available[i] = node.available_vec()
            index[node.id] = i
        # padding rows have zero capacity and are masked infeasible anyway
        t = cls(nodes=list(nodes), n_pad=n_pad, available=available,
                used=used, node_index=index)
        t.refresh_usage(ctx)
        return t

    def refresh_usage(self, ctx: EvalContext) -> None:
        """Proposed usage (state - evictions + placements). Base usage
        comes from the store's per-node usage rows — O(nodes) reads, not
        an O(allocs) rescan — and only nodes the in-progress plan touches
        are recomputed from ctx.proposed_allocs (reference context.go:176
        ProposedAllocs). Called between task groups so group B sees group
        A's in-plan placements."""
        snap = ctx.snapshot
        used = self.used
        used[:] = 0.0
        for i, node in enumerate(self.nodes):
            u = snap.node_usage(node.id)
            if u is not None:
                used[i] = u
        plan = ctx.plan
        if plan is None:
            return
        touched = (set(plan.node_update) | set(plan.node_preemptions)
                   | set(plan.node_allocation))
        for node_id in touched:
            i = self.node_index.get(node_id)
            if i is None:
                continue
            used[i] = 0.0
            for a in ctx.proposed_allocs(node_id):
                if a.should_count_for_usage():
                    used[i] += a.allocated_vec

    def placement_counts(self, job: Job, tg: TaskGroup,
                         ctx: EvalContext) -> Tuple[np.ndarray, np.ndarray]:
        """(placed_tg, placed_job) int32 vectors counting this job's
        proposed allocs per node (anti-affinity + distinct_hosts inputs).
        Walks only this job's allocs plus the plan — not every alloc."""
        ptg = np.zeros(self.n_pad, dtype=np.int32)
        pjob = np.zeros(self.n_pad, dtype=np.int32)
        plan = ctx.plan
        removed: set = set()
        placed_ids: set = set()
        if plan is not None:
            for allocs in plan.node_update.values():
                removed.update(a.id for a in allocs)
            for allocs in plan.node_preemptions.values():
                removed.update(a.id for a in allocs)
            for allocs in plan.node_allocation.values():
                placed_ids.update(a.id for a in allocs)
        for a in ctx.snapshot.allocs_by_job(job.id, job.namespace):
            if a.terminal_status() or a.id in removed or a.id in placed_ids:
                continue
            i = self.node_index.get(a.node_id)
            if i is None:
                continue
            pjob[i] += 1
            if a.task_group == tg.name:
                ptg[i] += 1
        if plan is not None:
            for node_id, allocs in plan.node_allocation.items():
                i = self.node_index.get(node_id)
                if i is None:
                    continue
                for a in allocs:
                    if a.job_id != job.id or a.namespace != job.namespace:
                        continue
                    pjob[i] += 1
                    if a.task_group == tg.name:
                        ptg[i] += 1
        return ptg, pjob


@dataclass
class TaskGroupTensors:
    """Everything kernels.solve_task_group needs for one task group."""

    ask: np.ndarray                 # (D,)
    feasible: np.ndarray            # (Np,) bool
    affinity_boost: np.ndarray      # (Np,)
    placed_tg: np.ndarray           # (Np,) int32
    placed_job: np.ndarray          # (Np,) int32
    spread_val_id: np.ndarray       # (S, Np) int32
    spread_val_ok: np.ndarray       # (S, Np) bool
    spread_counts: np.ndarray       # (S, V) int32
    spread_desired: np.ndarray      # (S, V) float (NaN = no target)
    spread_has_targets: np.ndarray  # (S,) bool
    spread_weight: np.ndarray       # (S,)
    tg_count: float
    dh_job: bool
    dh_tg: bool
    spread_alg: bool
    # device/core count columns appended to the dense resource dims
    # (E = n device asks + 1 if reserved cores are requested)
    extra_cap: np.ndarray = None    # (Np, E)
    extra_used: np.ndarray = None   # (Np, E)
    extra_ask: np.ndarray = None    # (E,)
    dev_affinity: np.ndarray = None  # (Np,) device-affinity sub-score
    # distinct_property cap tables (reference propertyset.go)
    dp_val_id: np.ndarray = None    # (P, Np) int32
    dp_val_ok: np.ndarray = None    # (P, Np) bool
    dp_counts: np.ndarray = None    # (P, Vd) int32
    dp_limit: np.ndarray = None     # (P,)


def _affinity_vector(ctx: EvalContext, job: Job, tg: TaskGroup,
                     nodes: Sequence[Node], n_pad: int) -> np.ndarray:
    """Precompute the node-affinity boost per node
    (reference rank.go:710 NodeAffinityIterator, sum(weight)/sum|weight|)."""
    affinities = (list(job.affinities) + list(tg.affinities)
                  + [a for t in tg.tasks for a in t.affinities])
    out = np.zeros(n_pad)
    if not affinities:
        return out
    total_weight = sum(abs(a.weight) for a in affinities) or 1.0
    for i, node in enumerate(nodes):
        total = 0.0
        for aff in affinities:
            lval, lok = resolve_target(aff.ltarget, node)
            rval, rok = resolve_target(aff.rtarget, node)
            if check_constraint(aff.operand, lval, rval, lok, rok,
                                ctx.regex_cache, ctx.version_cache):
                total += aff.weight
        out[i] = total / total_weight
    return out


def _spread_tensors(ctx: EvalContext, job: Job, tg: TaskGroup,
                    nodes: Sequence[Node], n_pad: int):
    """Intern spread-attribute values and lower desired/existing counts
    (reference spread.go computeSpreadInfo + propertyset.go)."""
    spreads = combined_spreads(job, tg)
    s = len(spreads)
    if s == 0:
        z = np.zeros((0, n_pad), dtype=np.int32)
        return (z, np.zeros((0, n_pad), dtype=bool),
                np.zeros((0, 1), dtype=np.int32), np.full((0, 1), np.nan),
                np.zeros(0, dtype=bool), np.zeros(0))

    sum_weights = sum(abs(sp.weight) for sp in spreads) or 1.0
    existing = [a for a in ctx.snapshot.allocs_by_job(job.id, job.namespace)
                if not a.terminal_status() and a.task_group == tg.name]

    vocabs: List[Dict[str, int]] = []
    val_ids = np.zeros((s, n_pad), dtype=np.int32)
    val_ok = np.zeros((s, n_pad), dtype=bool)
    counts_list: List[Dict[int, int]] = []

    for si, sp in enumerate(spreads):
        vocab: Dict[str, int] = {}

        def intern(v: str) -> int:
            if v not in vocab:
                vocab[v] = len(vocab)
            return vocab[v]

        for i, node in enumerate(nodes):
            v, ok = resolve_target(sp.attribute, node)
            if ok:
                val_ids[si, i] = intern(v)
                val_ok[si, i] = True
        counts: Dict[int, int] = {}
        for a in existing:
            anode = ctx.snapshot.node_by_id(a.node_id)
            if anode is None:
                continue
            v, ok = resolve_target(sp.attribute, anode)
            if ok:
                vid = intern(v)
                counts[vid] = counts.get(vid, 0) + 1
        vocabs.append(vocab)
        counts_list.append(counts)

    v_pad = _pad_pow2(max(max(len(v) for v in vocabs), 1), floor=1)
    spread_counts = np.zeros((s, v_pad), dtype=np.int32)
    spread_desired = np.full((s, v_pad), np.nan)
    has_targets = np.zeros(s, dtype=bool)
    weights = np.zeros(s)

    for si, sp in enumerate(spreads):
        weights[si] = sp.weight / sum_weights
        for vid, c in counts_list[si].items():
            spread_counts[si, vid] = c
        if not sp.targets:
            continue
        has_targets[si] = True
        # desired-count semantics live in SpreadInfo (reference
        # spread.go:268 computeSpreadInfo) — reuse, don't re-derive
        desired = SpreadInfo(sp, tg.count).desired_counts
        implicit = desired.get(IMPLICIT_TARGET)
        for val, vid in vocabs[si].items():
            if val in desired:
                spread_desired[si, vid] = desired[val]
            elif implicit is not None:
                spread_desired[si, vid] = implicit
    return val_ids, val_ok, spread_counts, spread_desired, has_targets, weights


def _device_core_tensors(ctx: EvalContext, tg: TaskGroup,
                         cluster: ClusterTensors):
    """Per-ask device capacity/usage columns + a reserved-cores column +
    the device-affinity sub-score vector. Capacity is constraint-filtered
    per ask (reference feasible.go:1259 DeviceChecker + device.go); usage
    comes from the store's device-usage rows plus plan deltas.

    Count-fit on the device is intentionally slightly optimistic when
    several asks share one group's instances or NUMA "require" constrains
    core identity: the post-solve host assignment catches those and falls
    back per request (same contract as exact port numbers)."""
    from ..scheduler.devices import (accumulate_dev_usage,
                                     combined_numa_affinity,
                                     device_affinity_boost, groups_capacity,
                                     matching_groups)

    ask_res = tg.combined_resources()
    asks = ask_res.devices
    cores = int(ask_res.cores)
    e = len(asks) + (1 if cores else 0)
    nodes = cluster.nodes
    n_pad = cluster.n_pad
    if e == 0:
        z = np.zeros((n_pad, 0))
        return z, z, np.zeros(0), np.zeros(n_pad), "none"

    snap = ctx.snapshot
    cap = np.zeros((n_pad, e))
    used = np.zeros((n_pad, e))
    dev_aff = np.zeros(n_pad)
    any_affinities = any(a.affinities for a in asks)
    plan = ctx.plan
    touched = set()
    if plan is not None:
        touched = (set(plan.node_update) | set(plan.node_preemptions)
                   | set(plan.node_allocation))
    for i, node in enumerate(nodes):
        if node.id in touched:
            row = {}
            for a in ctx.proposed_allocs(node.id):
                accumulate_dev_usage(row, a)
        else:
            row = snap.node_dev_usage(node.id) or {}
        for ei, ask in enumerate(asks):
            groups = matching_groups(node, ask, ctx.regex_cache,
                                     ctx.version_cache)
            cap[i, ei] = groups_capacity(groups)
            used[i, ei] = sum(row.get(g.id, 0) for g in groups)
        if cores:
            cap[i, -1] = node.resources.total_cores
            used[i, -1] = row.get("cores", 0)
        if any_affinities:
            dev_aff[i] = device_affinity_boost(node, asks, ctx.regex_cache,
                                               ctx.version_cache)
    extra_ask = np.array([float(a.count) for a in asks]
                         + ([float(cores)] if cores else []))
    return cap, used, extra_ask, dev_aff, combined_numa_affinity(tg)


def _distinct_property_tensors(ctx: EvalContext, job: Job, tg: TaskGroup,
                               nodes, n_pad: int):
    """Interned distinct_property values + proposed counts + limits.
    Counts mirror the host mask's inputs (scheduler/rank.py
    _plan_aware_job_allocs -> feasible.distinct_property_mask): the job's
    live allocs as the in-progress plan would leave them."""
    from ..scheduler.feasible import distinct_property_constraints
    from ..scheduler.rank import _plan_aware_job_allocs

    constraints = distinct_property_constraints(job, tg)
    p = len(constraints)
    if p == 0:
        z = np.zeros((0, n_pad), dtype=np.int32)
        return (z, np.zeros((0, n_pad), dtype=bool),
                np.zeros((0, 1), dtype=np.int32), np.zeros(0))

    live = [a for a in _plan_aware_job_allocs(ctx, job)
            if not a.terminal_status()]
    val_ids = np.zeros((p, n_pad), dtype=np.int32)
    val_ok = np.zeros((p, n_pad), dtype=bool)
    limits = np.zeros(p)
    counts_list = []
    vocabs = []
    for pi, c in enumerate(constraints):
        try:
            limits[pi] = int(c.rtarget) if c.rtarget else 1
        except ValueError:
            limits[pi] = 1
        vocab: Dict[str, int] = {}

        def intern(v: str) -> int:
            if v not in vocab:
                vocab[v] = len(vocab)
            return vocab[v]

        for i, node in enumerate(nodes):
            v, ok = resolve_target(c.ltarget, node)
            if ok:
                val_ids[pi, i] = intern(v)
                val_ok[pi, i] = True
        counts: Dict[int, int] = {}
        for a in live:
            anode = ctx.snapshot.node_by_id(a.node_id)
            if anode is None:
                continue
            v, ok = resolve_target(c.ltarget, anode)
            if ok and v in vocab:
                counts[vocab[v]] = counts.get(vocab[v], 0) + 1
        vocabs.append(vocab)
        counts_list.append(counts)
    v_pad = _pad_pow2(max(max(len(v) for v in vocabs), 1), floor=1)
    dp_counts = np.zeros((p, v_pad), dtype=np.int32)
    for pi, counts in enumerate(counts_list):
        for vid, cnt in counts.items():
            dp_counts[pi, vid] = cnt
    return val_ids, val_ok, dp_counts, limits


def build_task_group_tensors(
    ctx: EvalContext,
    job: Job,
    tg: TaskGroup,
    cluster: ClusterTensors,
    *,
    algorithm: str = enums.SCHED_ALG_BINPACK,
) -> TaskGroupTensors:
    nodes = cluster.nodes
    n_pad = cluster.n_pad

    feas = np.zeros(n_pad, dtype=bool)
    feas[: len(nodes)] = feasible_mask(job, tg, nodes,
                                       ctx.regex_cache, ctx.version_cache,
                                       snapshot=ctx.snapshot, plan=ctx.plan)
    placed_tg, placed_job = cluster.placement_counts(job, tg, ctx)
    (val_id, val_ok, counts, desired,
     has_targets, weights) = _spread_tensors(ctx, job, tg, nodes, n_pad)
    dh_job, dh_tg = distinct_hosts_flags(job, tg)

    # Reserved ports: conflict-free nodes only, and at most one alloc of
    # this group per node (the group's second alloc would collide with
    # the first's static ports) — which is exactly the dh_tg constraint
    # the kernel already enforces. Dynamic-port exhaustion is the R_PORTS
    # dimension of ask/available; exact numbers assigned post-solve.
    if tg.combined_resources().reserved_port_asks():
        feas[: len(nodes)] &= reserved_ports_mask(tg, nodes, ctx.proposed_allocs)
        dh_tg = True

    extra_cap, extra_used, extra_ask, dev_aff, _ = _device_core_tensors(
        ctx, tg, cluster)
    dp_val_id, dp_val_ok, dp_counts, dp_limit = _distinct_property_tensors(
        ctx, job, tg, nodes, n_pad)

    return TaskGroupTensors(
        ask=tg.combined_resources().vec(),
        feasible=feas,
        affinity_boost=_affinity_vector(ctx, job, tg, nodes, n_pad),
        placed_tg=placed_tg,
        placed_job=placed_job,
        spread_val_id=val_id,
        spread_val_ok=val_ok,
        spread_counts=counts,
        spread_desired=desired,
        spread_has_targets=has_targets,
        spread_weight=weights,
        tg_count=float(max(tg.count, 1)),
        dh_job=dh_job,
        dh_tg=dh_tg,
        spread_alg=(algorithm == enums.SCHED_ALG_SPREAD),
        extra_cap=extra_cap,
        extra_used=extra_used,
        extra_ask=extra_ask,
        dev_affinity=dev_aff,
        dp_val_id=dp_val_id,
        dp_val_ok=dp_val_ok,
        dp_counts=dp_counts,
        dp_limit=dp_limit,
    )
