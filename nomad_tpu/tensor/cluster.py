"""Tensorization: snapshot + in-progress plan -> dense arrays.

The piece with no reference analog (SURVEY.md §7 stage 2): lowers the
object-graph view the host scheduler walks (nodes, proposed allocs,
constraints, spreads) into the padded arrays kernels.py consumes.

Constraint semantics stay host-side — regex/version/semver operators are
evaluated once per *unique attribute value* by the vectorized masks in
scheduler.feasible (the tensor-era form of the reference's computed-node-
class memoization, context.go:261) — and only the resulting boolean masks
and interned value-id tables ship to the device.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..structs import Job, Node, TaskGroup, enums
from ..structs.resources import RESOURCE_DIMS
from ..scheduler.context import EvalContext
from ..scheduler.feasible import (
    check_constraint,
    distinct_hosts_flags,
    feasible_mask,
    feasible_mask_static,
    csi_volume_mask,
    reserved_ports_mask,
    resolve_target,
    tg_mask_signature,
)
from ..scheduler.spread import IMPLICIT_TARGET, SpreadInfo, combined_spreads
from .incremental import feed_for
from .overlay import INFLIGHT


def _pad_pow2(n: int, floor: int = 8) -> int:
    out = floor
    while out < n:
        out *= 2
    return out


class NodeSlotRegistry:
    """Stable node→slot assignment with a free-list, one per store: a
    node keeps its slot for as long as it exists, a deleted node's slot
    is recycled to the next joiner (lowest free slot first, so the slot
    space stays dense under churn). The incremental feed keys its
    epochs on row LAYOUT — today's statics still order rows by the
    dense ready-list, so membership changes resync — but the registry
    pins the identity the resync path and the join/leave tests reason
    about, and is the anchor for the layout-stable statics stretch
    (ROADMAP): a static ordering rows by slot would keep epochs alive
    across joins/leaves entirely."""

    def __init__(self):
        self._slots: Dict[str, int] = {}
        self._free: List[int] = []
        self._next = 0
        self._lock = threading.Lock()

    def assign(self, node_ids: Sequence[str], store=None) -> Dict[str, int]:
        """Slot per node id, allocating for new ids. When `store` is
        given, slots of nodes deleted from it are released first (the
        one authoritative leave signal; drained-but-present nodes keep
        their slot)."""
        import heapq

        with self._lock:
            if store is not None:
                for nid in [n for n in self._slots
                            if store._nodes.get_latest(n) is None]:
                    heapq.heappush(self._free, self._slots.pop(nid))
            out: Dict[str, int] = {}
            for nid in node_ids:
                s = self._slots.get(nid)
                if s is None:
                    if self._free:
                        s = heapq.heappop(self._free)
                    else:
                        s = self._next
                        self._next += 1
                    self._slots[nid] = s
                out[nid] = s
            return out

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"assigned": len(self._slots), "free": len(self._free),
                    "high_water": self._next}


class ClusterStatic:
    """Canonical per-(node-set version, node list) arrays shared across
    evals AND scheduler workers: everything here depends only on node
    identity/attributes — capacity, index maps, feasibility masks,
    affinity vectors, attribute-value interning — never on usage or
    plans. Keyed by the store's node_set_version; one node write anywhere
    invalidates the whole set.

    This is the round-4 resident layer: round 3 rebuilt every one of
    these O(nodes) Python-side arrays once per eval, which dominated the
    eval hot path at 10K nodes."""

    __slots__ = ("nodes", "n_pad", "available", "node_index", "usage_rows",
                 "version", "mask_cache", "aff_cache", "intern_cache",
                 "dev_cache", "device_arrays", "slots")

    def __init__(self, nodes: Sequence[Node], store=None, version=None):
        n = len(nodes)
        self.nodes = list(nodes)
        self.n_pad = _pad_pow2(n)
        self.version = version
        self.available = np.zeros((self.n_pad, RESOURCE_DIMS))
        self.node_index: Dict[str, int] = {}
        for i, node in enumerate(nodes):
            self.available[i] = node.available_vec()
            self.node_index[node.id] = i
        self.usage_rows = (store.usage_rows_for([n.id for n in nodes])
                           if store is not None and n else None)
        # stable per-store node→slot identity (see NodeSlotRegistry);
        # None for uncached per-eval statics with no store behind them
        self.slots = None
        self.mask_cache: Dict[tuple, np.ndarray] = {}
        self.aff_cache: Dict[tuple, np.ndarray] = {}
        self.intern_cache: Dict[tuple, tuple] = {}
        self.dev_cache: Dict[tuple, tuple] = {}
        # device-RESIDENT copies of static arrays (capacity, masks,
        # affinity vectors), uploaded once per node-set version so the
        # bulk solve ships only its per-eval dynamic matrix over the
        # tunnel (tensor/kernels.py solve_bulk_fused)
        self.device_arrays: Dict = {}


# one build at a time cluster-wide: builds are keyed per (version, node
# set) and idempotent, so a global lock (not per-store) is fine
_static_build_lock = threading.Lock()


def _static_for(ctx: EvalContext, nodes: Sequence[Node]):
    """Cached ClusterStatic when `nodes` is the canonical ready-node list
    (see StateSnapshot.ready_nodes_in_pool); None otherwise."""
    store = getattr(ctx.snapshot, "_store", None)
    if store is None:
        return None
    version = getattr(nodes, "canonical_version", None)
    if version is None or version != store.node_set_version:
        return None
    statics = getattr(store, "_tensor_statics", None)
    if statics is None:
        statics = store._tensor_statics = {}
    key = (version, getattr(nodes, "canonical_key", None))
    static = statics.get(key)
    if static is None:
        # serialize the (expensive, O(nodes)) build so N workers racing
        # on the same key share ONE ClusterStatic instead of each
        # building a duplicate — with batched eval processing every
        # worker hits this on the same version at once
        with _static_build_lock:
            static = statics.get(key)
            if static is None:
                # drop stale versions (iterate a keys copy — readers are
                # concurrent)
                for k in [k for k in list(statics) if k[0] != version]:
                    statics.pop(k, None)
                static = ClusterStatic(nodes, store=store, version=version)
                registry = getattr(store, "_node_slots", None)
                if registry is None:
                    registry = store._node_slots = NodeSlotRegistry()
                static.slots = registry.assign(
                    [n.id for n in static.nodes], store=store)
                statics[key] = static
    return static


@dataclass
class ClusterTensors:
    """Per-eval view: shared ClusterStatic + this eval's usage state."""

    nodes: List[Node]
    n_pad: int
    available: np.ndarray          # (Np, D) shared with the static — read-only
    used: np.ndarray               # (Np, D) proposed usage, per-eval
    node_index: Dict[str, int]
    static: "ClusterStatic" = None
    _store: object = None
    # `used` is the incremental feed's shared read-only base (zero-copy
    # warm path); any write path must go through _ensure_private first
    _used_shared: bool = False

    @classmethod
    def build(cls, ctx: EvalContext, nodes: Sequence[Node]) -> "ClusterTensors":
        static = _static_for(ctx, nodes)
        if static is None:
            static = ClusterStatic(nodes)  # per-eval, uncached
        t = cls(nodes=static.nodes, n_pad=static.n_pad,
                available=static.available, used=None,
                node_index=static.node_index, static=static,
                _store=getattr(ctx.snapshot, "_store", None))
        t.refresh_usage(ctx)
        return t

    def _ensure_private(self) -> np.ndarray:
        """A privately-owned writable `used` of the right shape —
        allocates on first use, copies the shared feed base out of the
        way, reuses an existing private buffer otherwise."""
        u = self.used
        if u is None or u.shape[0] != self.n_pad:
            u = self.used = np.zeros((self.n_pad, RESOURCE_DIMS))
        elif self._used_shared or not u.flags.writeable:
            u = self.used = u.astype(np.float64, copy=True)
        self._used_shared = False
        return u

    def refresh_usage(self, ctx: EvalContext) -> None:
        """Proposed usage (state - evictions + placements). Base usage is
        one fancy-index gather from the store's dense usage matrix when
        available (latest-committed state: fresher than the snapshot,
        which only helps an optimistic solve — the serialized applier
        re-verifies), else O(nodes) snapshot rows. Only nodes the
        in-progress plan touches are recomputed from ctx.proposed_allocs
        (reference context.go:176 ProposedAllocs). Called between task
        groups so group B sees group A's in-plan placements."""
        snap = ctx.snapshot
        n = len(self.nodes)
        plan = ctx.plan
        touched = ()
        if plan is not None and (plan.node_update or plan.node_preemptions
                                 or plan.node_allocation):
            touched = (set(plan.node_update) | set(plan.node_preemptions)
                       | set(plan.node_allocation))
        # incremental fast path (tensor/incremental.py): the feed's
        # delta-fed base already IS latest-committed usage in this
        # static's row order. With no plan-touched rows and no racing
        # in-flight placements the base is handed out as a shared
        # read-only view — the O(N) gather disappears entirely from the
        # warm path; otherwise it seeds a copy-on-write private buffer.
        base = None
        if self._store is not None and self.static is not None:
            feed = feed_for(self._store)
            if feed is not None:
                base = feed.base_for(self.static)
        if base is not None:
            if not touched and not INFLIGHT.has_entries(
                    exclude_plan=ctx.plan):
                self.used = base
                self._used_shared = True
                return
            used = self.used = base.copy()
            self._used_shared = False
        else:
            used = self._ensure_private()
            rows = (self.static.usage_rows if self.static is not None
                    else None)
            if rows is not None and self._store is not None:
                used[:n] = self._store._usage_mat[rows]
                used[n:] = 0.0
            else:
                used[:] = 0.0
                for i, node in enumerate(self.nodes):
                    u = snap.node_usage(node.id)
                    if u is not None:
                        used[i] = u
        if plan is not None:
            for node_id in touched:
                i = self.node_index.get(node_id)
                if i is None:
                    continue
                used[i] = 0.0
                for a in ctx.proposed_allocs(node_id):
                    if a.should_count_for_usage():
                        used[i] += a.allocated_vec
        # other racing evals' in-flight (solved, not yet committed)
        # placements: fold LAST so this solve plans around them instead
        # of colliding on the same best-fit nodes (tensor/overlay.py;
        # the per-eval twin of the bulk solver service's carry)
        INFLIGHT.fold(used[:n], self.node_index,
                      exclude_plan=ctx.plan)

    def latest_usage(self) -> np.ndarray:
        """Freshly-gathered LATEST committed usage, (n_pad, D) float32.
        The bulk solver service calls this at RESYNC time (not solve
        time): a resync base captured when the eval started can be
        seconds stale under queue depth, and usage committed by solves
        whose ledger entries already closed would be lost from the
        carry — the round-5 oversubscription cascade."""
        rows = self.static.usage_rows if self.static is not None else None
        if rows is not None and self._store is not None:
            mat = self._store._usage_mat  # local ref: matrix may be
            # swapped by a concurrent restore (_rebuild_usage_matrix);
            # row assignments may then be stale — bounds-check and fall
            # back, the applier re-verifies either way
            if len(rows) == 0 or rows.max() < mat.shape[0]:
                out = np.zeros((self.n_pad, RESOURCE_DIMS), dtype=np.float32)
                out[: len(self.nodes)] = mat[rows]
                # per-eval in-flight placements (tensor/overlay.py) are
                # not in the store yet NOR in the service's own ledger —
                # fold them so a bulk resync can't double-book against
                # racing spread/constraint evals
                from .overlay import INFLIGHT

                INFLIGHT.fold(out[: len(self.nodes)], self.node_index)
                return out
        return self.used.astype(np.float32)

    def placement_counts(self, job: Job, tg: TaskGroup,
                         ctx: EvalContext) -> Tuple[np.ndarray, np.ndarray]:
        """(placed_tg, placed_job) int32 vectors counting this job's
        proposed allocs per node (anti-affinity + distinct_hosts inputs).
        Walks only this job's allocs plus the plan — not every alloc."""
        ptg = np.zeros(self.n_pad, dtype=np.int32)
        pjob = np.zeros(self.n_pad, dtype=np.int32)
        plan = ctx.plan
        removed: set = set()
        placed_ids: set = set()
        if plan is not None:
            for allocs in plan.node_update.values():
                removed.update(a.id for a in allocs)
            for allocs in plan.node_preemptions.values():
                removed.update(a.id for a in allocs)
            for allocs in plan.node_allocation.values():
                placed_ids.update(a.id for a in allocs)
        for a in ctx.snapshot.allocs_by_job(job.id, job.namespace):
            if a.terminal_status() or a.id in removed or a.id in placed_ids:
                continue
            i = self.node_index.get(a.node_id)
            if i is None:
                continue
            pjob[i] += 1
            if a.task_group == tg.name:
                ptg[i] += 1
        if plan is not None:
            for node_id, allocs in plan.node_allocation.items():
                i = self.node_index.get(node_id)
                if i is None:
                    continue
                for a in allocs:
                    if a.job_id != job.id or a.namespace != job.namespace:
                        continue
                    pjob[i] += 1
                    if a.task_group == tg.name:
                        ptg[i] += 1
        return ptg, pjob


@dataclass
class VictimTensors:
    """Per-node victim columns for the in-kernel preemption solve
    (kernels.preempt_solve): every eligible lower-priority alloc on a
    node becomes a column slot carrying its priority, allocated
    resource vector, eligibility, and an exact-resource flag
    (port/device holders the dense columns can't model — rows whose
    victim set touches one fall back to the exact host scanner).

    Built per (eval, task-group priority) snapshot — eligibility
    depends on the in-progress plan's proposed allocs, so unlike
    ClusterStatic these are NOT cacheable across evals. Column order is
    scheduler.preemption.victim_candidates' canonical order (priority
    asc, alloc id asc), which is exactly the prefix order the kernel
    consumes; `refs[i][v]` maps column v of node i back to the concrete
    Allocation. v_pad quantizes to powers of two (same G_PAD/K_PAD
    discipline as the solver service) so the production shape compiles
    once at warmup."""

    v_pad: int
    prio: np.ndarray       # (Np, V) f32, 0 on empty slots
    vec: np.ndarray        # (Np, V, D) f32 allocated resource vectors
    elig: np.ndarray       # (Np, V) bool
    flagged: np.ndarray    # (Np, V) bool port/device holders
    refs: List[List]       # per real node, column order
    evictable: np.ndarray  # (Np, D) f32 sum of eligible victim vectors
    net_prio: np.ndarray   # (Np,) f32 aggregate max + sum/max


def build_victim_tensors(ctx: EvalContext, cluster: "ClusterTensors",
                         current_priority: int,
                         v_floor: int = 8) -> VictimTensors:
    """Lower every node's preemptible-alloc set into padded victim
    columns + the per-node aggregates (evictable capacity, approximate
    netPriority) the node-choice score consumes. One pass over proposed
    allocs per node — this replaces the Python aggregate loops the old
    host preemption path re-ran per batch."""
    from ..scheduler.preemption import (victim_candidates,
                                        victim_holds_exact_resources)

    nodes = cluster.nodes
    n_pad = cluster.n_pad
    d = cluster.available.shape[1]
    per_node = [victim_candidates(ctx.proposed_allocs(node.id),
                                  current_priority) for node in nodes]
    v_max = max((len(c) for c in per_node), default=0)
    v_pad = _pad_pow2(max(v_max, 1), floor=v_floor)

    prio = np.zeros((n_pad, v_pad), dtype=np.float32)
    vec = np.zeros((n_pad, v_pad, d), dtype=np.float32)
    elig = np.zeros((n_pad, v_pad), dtype=bool)
    flagged = np.zeros((n_pad, v_pad), dtype=bool)
    max_p = np.zeros(n_pad, dtype=np.float32)
    sum_p = np.zeros(n_pad, dtype=np.float32)
    for i, cands in enumerate(per_node):
        for v, a in enumerate(cands):
            p = float(a.job.priority)
            prio[i, v] = p
            vec[i, v] = np.asarray(a.allocated_vec[:d], dtype=np.float32)
            elig[i, v] = True
            flagged[i, v] = victim_holds_exact_resources(a)
            sum_p[i] += p
            if p > max_p[i]:
                max_p[i] = p
    evictable = (vec * elig[:, :, None]).sum(axis=1)
    net_prio = np.where(max_p > 0,
                        max_p + sum_p / np.maximum(max_p, 1.0),
                        0.0).astype(np.float32)
    return VictimTensors(v_pad=v_pad, prio=prio, vec=vec, elig=elig,
                         flagged=flagged, refs=per_node,
                         evictable=evictable, net_prio=net_prio)


@dataclass
class TaskGroupTensors:
    """Everything kernels.solve_task_group needs for one task group."""

    ask: np.ndarray                 # (D,)
    feasible: np.ndarray            # (Np,) bool
    affinity_boost: np.ndarray      # (Np,)
    placed_tg: np.ndarray           # (Np,) int32
    placed_job: np.ndarray          # (Np,) int32
    spread_val_id: np.ndarray       # (S, Np) int32
    spread_val_ok: np.ndarray       # (S, Np) bool
    spread_counts: np.ndarray       # (S, V) int32
    spread_desired: np.ndarray      # (S, V) float (NaN = no target)
    spread_has_targets: np.ndarray  # (S,) bool
    spread_weight: np.ndarray       # (S,)
    tg_count: float
    dh_job: bool
    dh_tg: bool
    spread_alg: bool
    # device/core count columns appended to the dense resource dims
    # (E = n device asks + 1 if reserved cores are requested)
    extra_cap: np.ndarray = None    # (Np, E)
    extra_used: np.ndarray = None   # (Np, E)
    extra_ask: np.ndarray = None    # (E,)
    dev_affinity: np.ndarray = None  # (Np,) device-affinity sub-score
    # distinct_property cap tables (reference propertyset.go)
    dp_val_id: np.ndarray = None    # (P, Np) int32
    dp_val_ok: np.ndarray = None    # (P, Np) bool
    dp_counts: np.ndarray = None    # (P, Vd) int32
    dp_limit: np.ndarray = None     # (P,)
    # the SHARED cached mask instance when `feasible` is exactly the
    # static mask (no per-eval csi/ports adjustments): its identity keys
    # the device-resident copy for the bulk solve
    feas_base: np.ndarray = None


def _affinity_vector(ctx: EvalContext, job: Job, tg: TaskGroup,
                     cluster: ClusterTensors) -> np.ndarray:
    """Precompute the node-affinity boost per node
    (reference rank.go:710 NodeAffinityIterator, sum(weight)/sum|weight|).
    Depends only on node attributes — cached on the ClusterStatic by
    affinity signature."""
    nodes, n_pad = cluster.nodes, cluster.n_pad
    affinities = (list(job.affinities) + list(tg.affinities)
                  + [a for t in tg.tasks for a in t.affinities])
    static = cluster.static
    if not affinities:
        if static is not None:
            # a stable zero instance so the device-resident cache can
            # key on identity
            hit = static.aff_cache.get(())
            if hit is None:
                hit = static.aff_cache[()] = np.zeros(n_pad)
            return hit
        return np.zeros(n_pad)
    sig = tuple((a.ltarget, a.operand, a.rtarget, a.weight)
                for a in affinities)
    if static is not None:
        hit = static.aff_cache.get(sig)
        if hit is not None:
            return hit
    total_weight = sum(abs(a.weight) for a in affinities) or 1.0
    out = np.zeros(n_pad)
    for i, node in enumerate(nodes):
        total = 0.0
        for aff in affinities:
            lval, lok = resolve_target(aff.ltarget, node)
            rval, rok = resolve_target(aff.rtarget, node)
            if check_constraint(aff.operand, lval, rval, lok, rok,
                                ctx.regex_cache, ctx.version_cache):
                total += aff.weight
        out[i] = total / total_weight
    if static is not None:
        static.aff_cache[sig] = out
    return out


def _interned_attr(ctx: EvalContext, cluster: ClusterTensors,
                   attribute: str):
    """-> (vocab, val_id (Np,), val_ok (Np,)) for one node attribute,
    cached on the ClusterStatic. The vocab keeps growing as off-pool
    nodes' values get interned by callers (append-only, so cached val_id
    arrays stay valid)."""
    static = cluster.static
    key = ("attr", attribute)
    if static is not None:
        hit = static.intern_cache.get(key)
        if hit is not None:
            return hit
    vocab: Dict[str, int] = {}
    val_id = np.zeros(cluster.n_pad, dtype=np.int32)
    val_ok = np.zeros(cluster.n_pad, dtype=bool)
    for i, node in enumerate(cluster.nodes):
        v, ok = resolve_target(attribute, node)
        if ok:
            vid = vocab.setdefault(v, len(vocab))
            val_id[i] = vid
            val_ok[i] = True
    out = (vocab, val_id, val_ok)
    if static is not None:
        static.intern_cache[key] = out
    return out


_intern_lock = __import__("threading").Lock()


def _intern(vocab: Dict[str, int], v: str) -> int:
    """Append-only interning safe under concurrent workers sharing a
    cached vocab (double-checked under a lock so two threads can never
    mint the same id for different values)."""
    vid = vocab.get(v)
    if vid is None:
        with _intern_lock:
            vid = vocab.get(v)
            if vid is None:
                vid = len(vocab)
                vocab[v] = vid
    return vid


def _spread_tensors(ctx: EvalContext, job: Job, tg: TaskGroup,
                    cluster: ClusterTensors):
    """Intern spread-attribute values and lower desired/existing counts
    (reference spread.go computeSpreadInfo + propertyset.go). The
    per-node interning tables come from the ClusterStatic cache; only the
    existing-alloc counts (O(job allocs)) are computed per eval."""
    n_pad = cluster.n_pad
    spreads = combined_spreads(job, tg)
    s = len(spreads)
    if s == 0:
        z = np.zeros((0, n_pad), dtype=np.int32)
        return (z, np.zeros((0, n_pad), dtype=bool),
                np.zeros((0, 1), dtype=np.int32), np.full((0, 1), np.nan),
                np.zeros(0, dtype=bool), np.zeros(0))

    sum_weights = sum(abs(sp.weight) for sp in spreads) or 1.0
    existing = [a for a in ctx.snapshot.allocs_by_job(job.id, job.namespace)
                if not a.terminal_status() and a.task_group == tg.name]

    vocabs: List[Dict[str, int]] = []
    val_ids = np.zeros((s, n_pad), dtype=np.int32)
    val_ok = np.zeros((s, n_pad), dtype=bool)
    counts_list: List[Dict[int, int]] = []

    for si, sp in enumerate(spreads):
        vocab, vid_row, vok_row = _interned_attr(ctx, cluster, sp.attribute)
        val_ids[si] = vid_row
        val_ok[si] = vok_row
        counts: Dict[int, int] = {}
        for a in existing:
            anode = ctx.snapshot.node_by_id(a.node_id)
            if anode is None:
                continue
            v, ok = resolve_target(sp.attribute, anode)
            if ok:
                vid = _intern(vocab, v)
                counts[vid] = counts.get(vid, 0) + 1
        vocabs.append(vocab)
        counts_list.append(counts)

    # snapshot the (shared, concurrently-growing) vocabs ONCE: every vid
    # this eval references was interned above, so a stable items() copy
    # taken here bounds v_pad and survives other workers' later inserts
    vocab_items = [list(v.items()) for v in vocabs]
    v_pad = _pad_pow2(max(max(len(v) for v in vocab_items), 1), floor=1)
    spread_counts = np.zeros((s, v_pad), dtype=np.int32)
    spread_desired = np.full((s, v_pad), np.nan)
    has_targets = np.zeros(s, dtype=bool)
    weights = np.zeros(s)

    for si, sp in enumerate(spreads):
        weights[si] = sp.weight / sum_weights
        for vid, c in counts_list[si].items():
            spread_counts[si, vid] = c
        if not sp.targets:
            continue
        has_targets[si] = True
        # desired-count semantics live in SpreadInfo (reference
        # spread.go:268 computeSpreadInfo) — reuse, don't re-derive
        desired = SpreadInfo(sp, tg.count).desired_counts
        implicit = desired.get(IMPLICIT_TARGET)
        for val, vid in vocab_items[si]:
            if val in desired:
                spread_desired[si, vid] = desired[val]
            elif implicit is not None:
                spread_desired[si, vid] = implicit
    return val_ids, val_ok, spread_counts, spread_desired, has_targets, weights


def _device_core_tensors(ctx: EvalContext, tg: TaskGroup,
                         cluster: ClusterTensors):
    """Per-ask device capacity/usage columns + a reserved-cores column +
    the device-affinity sub-score vector. Capacity is constraint-filtered
    per ask (reference feasible.go:1259 DeviceChecker + device.go); usage
    comes from the store's device-usage rows plus plan deltas.

    Count-fit on the device is intentionally slightly optimistic when
    several asks share one group's instances or NUMA "require" constrains
    core identity: the post-solve host assignment catches those and falls
    back per request (same contract as exact port numbers)."""
    from ..scheduler.devices import (accumulate_dev_usage,
                                     combined_numa_affinity,
                                     device_affinity_boost, groups_capacity,
                                     matching_groups)

    ask_res = ctx.tg_resources(tg)
    asks = ask_res.devices
    cores = int(ask_res.cores)
    e = len(asks) + (1 if cores else 0)
    nodes = cluster.nodes
    n_pad = cluster.n_pad
    if e == 0:
        z = np.zeros((n_pad, 0))
        return z, z, np.zeros(0), np.zeros(n_pad), "none"

    snap = ctx.snapshot
    used = np.zeros((n_pad, e))
    any_affinities = any(a.affinities for a in asks)

    # capacity columns + device-affinity boost depend only on node
    # hardware and the ask — cached on the ClusterStatic by ask signature
    static = cluster.static
    sig = (tuple((a.name, a.count,
                  tuple((c.ltarget, c.operand, c.rtarget)
                        for c in a.constraints),
                  tuple((f.ltarget, f.operand, f.rtarget, f.weight)
                        for f in a.affinities))
                 for a in asks), bool(cores))
    cached = static.dev_cache.get(sig) if static is not None else None
    if cached is not None:
        cap, dev_aff, match_lists = cached
    else:
        cap = np.zeros((n_pad, e))
        dev_aff = np.zeros(n_pad)
        # per (node, ask) matched group ids, reused by the usage fill
        match_lists = [[()] * len(asks) for _ in range(len(nodes))]
        for i, node in enumerate(nodes):
            for ei, ask in enumerate(asks):
                groups = matching_groups(node, ask, ctx.regex_cache,
                                         ctx.version_cache)
                cap[i, ei] = groups_capacity(groups)
                match_lists[i][ei] = tuple(g.id for g in groups)
            if cores:
                cap[i, -1] = node.resources.total_cores
            if any_affinities:
                dev_aff[i] = device_affinity_boost(
                    node, asks, ctx.regex_cache, ctx.version_cache)
        if static is not None:
            static.dev_cache[sig] = (cap, dev_aff, match_lists)

    plan = ctx.plan
    touched = set()
    if plan is not None:
        touched = (set(plan.node_update) | set(plan.node_preemptions)
                   | set(plan.node_allocation))
    for i, node in enumerate(nodes):
        if node.id in touched:
            row = {}
            for a in ctx.proposed_allocs(node.id):
                accumulate_dev_usage(row, a)
        else:
            row = snap.node_dev_usage(node.id)
        if not row:
            continue
        for ei in range(len(asks)):
            used[i, ei] = sum(row.get(gid, 0) for gid in match_lists[i][ei])
        if cores:
            used[i, -1] = row.get("cores", 0)
    extra_ask = np.array([float(a.count) for a in asks]
                         + ([float(cores)] if cores else []))
    return cap, used, extra_ask, dev_aff, combined_numa_affinity(tg)


def _distinct_property_tensors(ctx: EvalContext, job: Job, tg: TaskGroup,
                               cluster: ClusterTensors):
    """Interned distinct_property values + proposed counts + limits.
    Counts mirror the host mask's inputs (scheduler/rank.py
    _plan_aware_job_allocs -> feasible.distinct_property_mask): the job's
    live allocs as the in-progress plan would leave them."""
    from ..scheduler.feasible import distinct_property_constraints
    from ..scheduler.rank import _plan_aware_job_allocs

    n_pad = cluster.n_pad
    constraints = distinct_property_constraints(job, tg)
    p = len(constraints)
    if p == 0:
        z = np.zeros((0, n_pad), dtype=np.int32)
        return (z, np.zeros((0, n_pad), dtype=bool),
                np.zeros((0, 1), dtype=np.int32), np.zeros(0))

    live = [a for a in _plan_aware_job_allocs(ctx, job)
            if not a.terminal_status()]
    val_ids = np.zeros((p, n_pad), dtype=np.int32)
    val_ok = np.zeros((p, n_pad), dtype=bool)
    limits = np.zeros(p)
    counts_list = []
    vocabs = []
    for pi, c in enumerate(constraints):
        try:
            limits[pi] = int(c.rtarget) if c.rtarget else 1
        except ValueError:
            limits[pi] = 1
        vocab, vid_row, vok_row = _interned_attr(ctx, cluster, c.ltarget)
        val_ids[pi] = vid_row
        val_ok[pi] = vok_row
        counts: Dict[int, int] = {}
        for a in live:
            anode = ctx.snapshot.node_by_id(a.node_id)
            if anode is None:
                continue
            v, ok = resolve_target(c.ltarget, anode)
            if ok and v in vocab:
                counts[vocab[v]] = counts.get(vocab[v], 0) + 1
        vocabs.append(vocab)
        counts_list.append(counts)
    v_pad = _pad_pow2(max(max(len(v) for v in vocabs), 1), floor=1)
    dp_counts = np.zeros((p, v_pad), dtype=np.int32)
    for pi, counts in enumerate(counts_list):
        for vid, cnt in counts.items():
            dp_counts[pi, vid] = cnt
    return val_ids, val_ok, dp_counts, limits


def build_task_group_tensors(
    ctx: EvalContext,
    job: Job,
    tg: TaskGroup,
    cluster: ClusterTensors,
    *,
    algorithm: str = enums.SCHED_ALG_BINPACK,
) -> TaskGroupTensors:
    nodes = cluster.nodes
    n_pad = cluster.n_pad

    static = cluster.static
    feas_base = None
    if static is not None:
        sig = tg_mask_signature(job, tg)
        base = static.mask_cache.get(sig)
        if base is None:
            base = np.zeros(n_pad, dtype=bool)
            base[: len(nodes)] = feasible_mask_static(
                job, tg, nodes, ctx.regex_cache, ctx.version_cache)
            base.setflags(write=False)
            static.mask_cache[sig] = base
        if any(v.type == "csi" for v in tg.volumes.values()):
            feas = base.copy()
            feas[: len(nodes)] &= csi_volume_mask(
                tg, nodes, ctx.snapshot, job.namespace, ctx.plan)
        else:
            # the cached padded mask itself: stable identity keys the
            # device-resident copy (placer bulk path). Copied before any
            # per-eval mutation (reserved-ports AND below).
            feas = base
            feas_base = base
    else:
        feas = np.zeros(n_pad, dtype=bool)
        feas[: len(nodes)] = feasible_mask(
            job, tg, nodes, ctx.regex_cache, ctx.version_cache,
            snapshot=ctx.snapshot, plan=ctx.plan)
    placed_tg, placed_job = cluster.placement_counts(job, tg, ctx)
    (val_id, val_ok, counts, desired,
     has_targets, weights) = _spread_tensors(ctx, job, tg, cluster)
    dh_job, dh_tg = distinct_hosts_flags(job, tg)

    # Reserved ports: conflict-free nodes only, and at most one alloc of
    # this group per node (the group's second alloc would collide with
    # the first's static ports) — which is exactly the dh_tg constraint
    # the kernel already enforces. Dynamic-port exhaustion is the R_PORTS
    # dimension of ask/available; exact numbers assigned post-solve.
    if ctx.tg_resources(tg).reserved_port_asks():
        feas = feas.copy()  # may be the shared read-only cached mask
        feas_base = None
        feas[: len(nodes)] &= reserved_ports_mask(tg, nodes, ctx.proposed_allocs)
        dh_tg = True

    extra_cap, extra_used, extra_ask, dev_aff, _ = _device_core_tensors(
        ctx, tg, cluster)
    dp_val_id, dp_val_ok, dp_counts, dp_limit = _distinct_property_tensors(
        ctx, job, tg, cluster)

    return TaskGroupTensors(
        ask=ctx.tg_vec(tg),
        feasible=feas,
        affinity_boost=_affinity_vector(ctx, job, tg, cluster),
        placed_tg=placed_tg,
        placed_job=placed_job,
        spread_val_id=val_id,
        spread_val_ok=val_ok,
        spread_counts=counts,
        spread_desired=desired,
        spread_has_targets=has_targets,
        spread_weight=weights,
        tg_count=float(max(tg.count, 1)),
        dh_job=dh_job,
        dh_tg=dh_tg,
        spread_alg=(algorithm == enums.SCHED_ALG_SPREAD),
        extra_cap=extra_cap,
        extra_used=extra_used,
        extra_ask=extra_ask,
        dev_affinity=dev_aff,
        dp_val_id=dp_val_id,
        dp_val_ok=dp_val_ok,
        dp_counts=dp_counts,
        dp_limit=dp_limit,
        feas_base=feas_base,
    )
