"""Global-batch assignment solver behind SchedulerAlgorithm="tpu-solve".

PR 5's `EvalBroker.dequeue_batch` hands each worker a fused batch of
evals sharing one snapshot, but the greedy tier still places them one
scan step at a time in arrival order — the batch's cross-eval packing
quality is left on the table. This module solves the whole batch as ONE
tensorized assignment problem (CvxCluster, arxiv 2605.01614: granular
allocation as one iterative formulation; arxiv 2511.08373: global
formulations dominate greedy on bin-pack quality):

  * build the (G, N) feasibility-mask x score matrix for every
    placement request across every eval in the batch (the same
    tensor/cluster.py builds and kernels.fit_scores the greedy tier
    uses — satellite-deduped so the two tiers cannot drift),
  * run iterative AUCTION rounds inside one jitted while_loop: each
    still-unsatisfied eval bids for its TOP-R nodes by score; per-node
    capacity conflicts are resolved by a price update on contested
    nodes (losers are pushed to their next-best nodes on the following
    round); each node's winning eval fills its won nodes to capacity
    in score order until its demand runs out; usage tensors are
    updated once per ROUND instead of once per alloc,
  * run the sequential greedy chain (`kernels._solve_bulk_multi_impl`,
    the exact "tpu-binpack" math) in the SAME launch and keep whichever
    whole-batch assignment scores better — so `tpu-solve` dominates the
    greedy tier on packing quality by construction, and the greedy arm
    doubles as the in-kernel fallback when the auction leaves demand
    unplaced (capacity-fragmented instances).

Convergence: every round the globally best (eval, node) bid wins its
node and places at least one allocation (its feasibility check already
proved one unit fits), so total remaining demand strictly decreases
while any request is placeable; the loop exits on MAX_ROUNDS, on zero
remaining demand, or on a fully stalled round. Measured
rounds-to-convergence on the bench shapes is in PERF.md
("Global-batch solve").

The packing-quality metric is order-independent on purpose: the score
of an assignment is sum over nodes of (allocs placed on the node) x
(final-state BestFit fitness of the node). Scoring the FINAL usage
state rewards consolidation without depending on the order placements
were made in — both arms of the portfolio are scored on the same
footing, and `packing_score_np` is the same formula the tests and the
bench recompute host-side.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .kernels import (NEG, TIE_JITTER, _fit_scores_xp, _pairwise_sum_xp,
                      _solve_bulk_multi_impl)

# Auction round budget. Each round fills at least one node to capacity
# (see convergence note above); bench batches (G <= 16 evals, 1K-10K
# nodes) converge in well under half this (PERF.md table).
MAX_ROUNDS = 64
# Nodes each request bids for per round. One-node-per-round auctions
# need ~nodes-touched rounds to drain a large demand (measured: the 10K
# rung hit the MAX_ROUNDS cap with demand left over); bidding for the
# top-R nodes at once and letting the winner fill them in score order
# cuts rounds to ~touched/R with the identical conflict rule.
TOP_R = 16
# Price bump applied to a node that received more than one bid in a
# round. Sized like TIE_JITTER: far below any meaningful score gap, so
# prices only re-order requests among near-equal nodes, never force a
# request onto a genuinely worse node ahead of a better free one.
PRICE_EPS = TIE_JITTER
# Restart portfolio: one (jitter_scale, price_temperature) pair per
# auction restart. The tie-break jitter decides which of many
# near-equal packings the auction converges to; restarting with fresh
# jitter and keeping the best-scoring assignment is a randomized
# restart portfolio over those basins. The packing score is pure
# fitness (jitter never enters it), so the max over restarts is a real
# quality improvement, and the auction is the cheap arm of the launch —
# the sequential greedy chain dominates its cost.
#
# The pairs are OFFLINE-FITTED frozen constants, not guesses: scripts/
# fit_portfolio.py replays seeded solver-shaped problems (the obs-plane
# trace shapes: nomad.eval.phase.* + the joint/greedy score pairs the
# Registry already records) and grid-searches (jitter_scale x
# price_temp) for the portfolio with the best auction-vs-greedy win
# rate at EQUAL restart count vs the old fixed five-identical-restarts
# schedule. jitter_scale multiplies the TIE_JITTER range each restart
# draws from (wider = hops basins more aggressively); price_temp
# multiplies PRICE_EPS (hotter = contested nodes repel losers harder,
# colder = bidders keep re-converging on near-full nodes). Entry 0 is
# pinned at (1.0, 1.0) — the legacy basin stays in the portfolio as its
# safety arm, so the fitted portfolio can only add basins, never lose
# the old one. Re-fit with: python scripts/fit_portfolio.py
#
# Fitted 2026-08 over 16 seeded contended problems (64 nodes x 8 evals,
# 55-95% fill): the fit consistently selects COLD price temperatures
# (0.25x) with spread jitter scales — under the BestFit objective the
# losers should keep re-converging on near-full nodes, and basin
# diversity comes from jitter width instead. Mean packing-score edge vs
# greedy improved from -28.59 (legacy five identical restarts) to
# -28.10 at equal restart count; greedy stays ahead on contended
# packings overall, which is exactly why it remains the in-kernel
# safety arm of the portfolio pick below. The duplicate (8.0, 0.25)
# entry is intentional: each slot draws a different fold_in(t) jitter
# stream, so a repeated pair is a fresh sample of its basin.
PORTFOLIO = (
    (1.0, 1.0),   # legacy basin (pinned)
    (8.0, 0.25),
    (0.25, 0.25),
    (4.0, 0.25),
    (8.0, 0.25),
)
RESTARTS = len(PORTFOLIO)


# _pairwise_sum_xp now lives in kernels (score_nodes needs it for the
# spread-presence reduction); re-exported here because sharding.py and
# the PR 14 determinism tests import it from this module.


def _packing_score_xp(xp, counts, available, used_final):
    """Order-independent packing quality of a whole-batch assignment:
    sum_n placed[n] * BestFit-fitness(available[n], used_final[n])."""
    per_node = _fit_scores_xp(xp, available, used_final, False)   # (N,)
    placed = counts.sum(axis=0) if counts.ndim == 2 else counts   # (N,)
    return _pairwise_sum_xp(xp, placed.astype(per_node.dtype) * per_node)


def packing_score_np(counts, available, used_final) -> float:
    """Numpy twin of the in-kernel portfolio metric — used by the
    property tests and the bench A/B rung to score end states."""
    return float(_packing_score_xp(
        np, np.asarray(counts), np.asarray(available, dtype=np.float64),
        np.asarray(used_final, dtype=np.float64)))


def _auction(used0, available, feas, aff, ask, k, jits, g: int, rounds: int,
             top_r: int = TOP_R, price_eps=PRICE_EPS,
             evict=None, pscore=None):
    """One jitted auction: per round each still-unsatisfied request bids
    for its TOP-R nodes by (score + jitter - price); each node accepts
    its best bidder (ties to the lowest eval index) and the winner fills
    its won nodes to capacity in score order until its demand runs out.
    Returns (used, (G, N) int32 take, rounds_run).

    `price_eps` is the per-restart price temperature (PORTFOLIO).
    `evict`/`pscore` thread the preemption victim columns through the
    joint solve: `evict` (N, D) is each node's victim budget — capacity
    reclaimable by evicting its preemptible column (tensor/cluster.
    build_victim_tensors) — and extends the bid/cap feasibility bound to
    available + evict, exhaustion-gated exactly like prices (the budget
    only pays out as `used` crosses `available`; sibling winners see the
    drained budget in the shared usage carry next round). `pscore` (N,)
    is the logistic preemption penalty those over-capacity bids carry
    (rank.go:894), so a preempting placement only beats a free node on
    genuine fit. Both None = the legacy victim-blind auction graph,
    bit-identical to before."""
    n, d = available.shape
    f = available.dtype
    r = min(top_r, n)
    avail_cap = available if evict is None else available + evict
    # int32 throughout the carry: under x64 (tests) arange defaults to
    # int64 and sum() promotes int32 -> int64, which breaks the
    # while_loop's fixed carry types
    g_idx = jnp.arange(g, dtype=jnp.int32)
    ask_pos = ask > 0                                             # (G, D)
    aff_present = aff != 0.0
    divisor = 1.0 + aff_present.astype(f)

    def body(state):
        used, remaining, take, price, rnd, _ = state
        # (G, N) bid matrix against the CURRENT usage state
        new_used = used[None, :, :] + ask[:, None, :]             # (G,N,D)
        ok = feas & jnp.all(new_used <= avail_cap[None, :, :], axis=2)
        ok &= (remaining > 0)[:, None]
        if evict is None:
            fitness = _fit_scores_xp(jnp, available[None, :, :], new_used,
                                     False)                       # (G, N)
            score = (fitness + jnp.where(aff_present, aff, 0.0)) / divisor
        else:
            # over-capacity bids spend victim budget: fitness is scored
            # against true capacity (min-clamped, the preempt_solve
            # convention) and carries the preemption penalty term
            fitness = _fit_scores_xp(
                jnp, available[None, :, :],
                jnp.minimum(new_used, available[None, :, :]), False)
            over = jnp.any(new_used > available[None, :, :], axis=2)
            score = (fitness + jnp.where(aff_present, aff, 0.0)
                     + jnp.where(over, pscore[None, :], 0.0)) / (
                         divisor + over.astype(f))
        bid = jnp.where(ok, score + jits - price[None, :], NEG)
        # each request's R best nodes, descending (top_k is stable:
        # ties go to the lower node index on every layout)
        vals, idxs = jax.lax.top_k(bid, r)                        # (G, R)
        active = vals > NEG / 2
        flat_idx = idxs.reshape(-1)
        flat_val = jnp.where(active, vals, NEG).reshape(-1)
        flat_g = jnp.broadcast_to(g_idx[:, None], (g, r)).reshape(-1)
        # winner per node: highest bid among all surfaced candidates,
        # residual ties to the lowest eval index (deterministic
        # regardless of scatter order)
        node_best = jnp.full(n, NEG, f).at[flat_idx].max(flat_val)
        is_best = (flat_val > NEG / 2) & (flat_val >= node_best[flat_idx])
        node_winner = jnp.full(n, g, jnp.int32).at[flat_idx].min(
            jnp.where(is_best, flat_g, g))
        won = active & (vals >= node_best[idxs]) & (
            node_winner[idxs] == g_idx[:, None])                  # (G, R)
        # capacity of each won node (BestFit fill — the same budget
        # rule as the greedy chain's sorted fill)
        free = avail_cap[idxs] - used[idxs]                       # (G,R,D)
        per_dim = jnp.where(
            ask_pos[:, None, :],
            jnp.floor(free / jnp.where(ask_pos, ask, 1.0)[:, None, :]),
            jnp.inf)
        cap = jnp.clip(jnp.min(per_dim, axis=2), 0, None)
        cap = jnp.where(won, cap, 0.0)                            # (G, R)
        # spend the remaining demand across won nodes in score order
        prefix = jnp.cumsum(cap, axis=1) - cap
        amt = jnp.clip(remaining.astype(cap.dtype)[:, None] - prefix,
                       0.0, cap).astype(jnp.int32)                # (G, R)
        # one scatter per ROUND: won nodes are distinct across all
        # (eval, slot) pairs, losers contribute zero rows
        used = used.at[flat_idx].add(
            (ask[:, None, :] * amt[..., None].astype(f)).reshape(-1, d))
        take = take.at[g_idx[:, None], idxs].add(amt)
        remaining = remaining - amt.sum(axis=1, dtype=jnp.int32)
        # price update: a capacity conflict is only real when the round
        # EXHAUSTED the node (the winner drained all it could hold) —
        # only then do this round's losers pay to go elsewhere. Pricing
        # every contested node (the classic rule) actively spreads
        # bidders away from the fullest feasible nodes, which is
        # anti-packing under a BestFit objective; with exhaustion-gated
        # prices the losers re-converge on near-full nodes next round,
        # so the auction behaves as a synchronized global BestFit that
        # interleaves heterogeneous asks per node — the axis on which
        # it beats the per-eval greedy chain
        bids_per_node = jnp.zeros(n, jnp.int32).at[flat_idx].add(
            active.reshape(-1).astype(jnp.int32))
        filled = won & (cap > 0) & (amt.astype(cap.dtype) >= cap)
        node_filled = jnp.zeros(n, jnp.bool_).at[flat_idx].max(
            filled.reshape(-1))
        price = price + price_eps * (
            node_filled & (bids_per_node > 1)).astype(f)
        return (used, remaining, take, price, rnd + 1, jnp.any(amt > 0))

    def cond(state):
        _, remaining, _, _, rnd, progressed = state
        return (rnd < rounds) & progressed & jnp.any(remaining > 0)

    init = (used0, k.astype(jnp.int32), jnp.zeros((g, n), jnp.int32),
            jnp.zeros(n, f), jnp.int32(0), jnp.bool_(True))
    used, _, take, _, rnd, _ = jax.lax.while_loop(cond, body, init)
    return used, take, rnd


@partial(jax.jit, static_argnames=("g", "rounds"), donate_argnums=(0,))
def solve_batch(
    used0,       # (N, D) f32 usage carry — device-RESIDENT, donated back
    available,   # (N, D) f32 resident capacity
    feas,        # (G, N) bool stacked per-eval feasibility masks
    aff,         # (G, N) f32 stacked per-eval affinity boosts
    ask,         # (G, D) f32 per-eval resource asks
    k,           # (G,) int32 placements wanted per eval
    tg_count,    # (G,) f32 (signature parity with solve_bulk_multi)
    seeds,       # (G,) uint32 per-eval tie-break seeds
    cidx,        # (C,) int32 usage-correction node rows (0 = no-op slot)
    cdelta,      # (C, D) f32 usage-correction deltas (see solver.py)
    evict=None,  # (N, D) f32 victim budgets (build_victim_tensors
                 #       .evictable) — None = victim-blind legacy graph
    net_prio=None,  # (N,) f32 preemptible-set netPriority aggregate
    *,
    g: int,
    rounds: int = MAX_ROUNDS,
):
    """Solve G evals' placements as ONE assignment problem -> ((N, D)
    new usage carry staying on device, (G, N) int16 per-eval counts,
    (6,) f32 info row — the counts + info pair is the only readback).

    Signature-compatible with kernels.solve_bulk_multi so the
    BulkSolverService can route a batch through either tier. Runs BOTH
    the auction and the exact greedy chain from the same start state
    inside this one launch and returns whichever assignment wins on
    (total placed, packing score) — per-eval rows keep their own counts
    either way, so per-job plan boundaries survive downstream.

    With `evict`/`net_prio` the auction arm also bids over each node's
    preemption victim budget (extra reclaimable capacity, penalty-scored
    and exhaustion-gated — see _auction); the greedy chain stays
    victim-blind by design, so the portfolio's safety arm never commits
    an assignment that needs evictions to be legal.

    info row: [auction_score, greedy_score, placed_auction,
    placed_greedy, rounds_run, auction_won].
    """
    n, d = available.shape
    f = available.dtype
    used0 = jnp.maximum(used0.at[cidx].add(cdelta), 0.0)
    pscore = (None if net_prio is None else
              1.0 / (1.0 + jnp.exp(0.0048 * (net_prio - 2048.0))))

    # greedy arm: the exact tpu-binpack chain, corrections already
    # folded above so the impl's fold sees no-op slots
    zero_cidx = jnp.zeros(1, jnp.int32)
    zero_cdelta = jnp.zeros((1, d), f)
    used_greedy, counts_greedy = _solve_bulk_multi_impl(
        used0, available, feas, aff, ask, k, tg_count, seeds,
        zero_cidx, zero_cdelta, g=g)

    # auction arm: one run per PORTFOLIO entry from the same start state
    # with fresh tie-break jitter each time (scaled per entry); keep the
    # lexicographically best (placed, score) assignment, earliest
    # restart on exact ties. Unrolled python loop (not vmap) so the
    # sharded mirror in sharding.py can use the identical selection
    # chain bit-for-bit, and so each restart's (jitter_scale,
    # price_temp) bakes in as trace-time constants.
    used_auction = take = rnd = None
    score_best = placed_best = None
    for t, (jscale, ptemp) in enumerate(PORTFOLIO):
        jits = jax.vmap(
            lambda s, _t=t, _js=jscale: jax.random.uniform(
                jax.random.fold_in(jax.random.PRNGKey(s), _t), (n,),
                jnp.float32, 0.0, TIE_JITTER * _js)
        )(seeds)                                                  # (G, N)
        used_t, take_t, rnd_t = _auction(
            used0, available, feas, aff, ask, k, jits, g, rounds,
            price_eps=PRICE_EPS * ptemp, evict=evict, pscore=pscore)
        # dtype pin: placement counts reduce as int32 (associative adds
        # — legal before a comparison; x64 would promote to int64)
        placed_t = take_t.sum(dtype=jnp.int32)
        score_t = _packing_score_xp(jnp, take_t, available, used_t)
        if t == 0:
            used_auction, take, rnd = used_t, take_t, rnd_t
            score_best, placed_best = score_t, placed_t
        else:
            better = (placed_t > placed_best) | (
                (placed_t == placed_best) & (score_t > score_best))
            used_auction = jnp.where(better, used_t, used_auction)
            take = jnp.where(better, take_t, take)
            rnd = jnp.where(better, rnd_t, rnd)
            score_best = jnp.where(better, score_t, score_best)
            placed_best = jnp.where(better, placed_t, placed_best)

    placed_a = take.sum(dtype=jnp.int32)
    placed_g = counts_greedy.astype(jnp.int32).sum()
    score_a = _packing_score_xp(jnp, take, available, used_auction)
    score_g = _packing_score_xp(jnp, counts_greedy.astype(jnp.int32),
                                available, used_greedy)
    # portfolio pick: more placements first, then packing score — the
    # selected assignment is never worse than greedy on either axis
    pick_a = (placed_a > placed_g) | (
        (placed_a == placed_g) & (score_a > score_g))
    used = jnp.where(pick_a, used_auction, used_greedy)
    counts = jnp.where(pick_a, take.astype(jnp.int16), counts_greedy)
    info = jnp.stack([
        score_a.astype(jnp.float32), score_g.astype(jnp.float32),
        placed_a.astype(jnp.float32), placed_g.astype(jnp.float32),
        rnd.astype(jnp.float32), pick_a.astype(jnp.float32)])
    return used, counts, info
